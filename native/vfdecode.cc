// vfdecode — native video decode service for video_features_tpu.
//
// TPU-native replacement for the reference's native decode path (the
// reference shells out to ffmpeg binaries and decodes through OpenCV's
// VideoCapture — reference utils/io.py:96-154, utils/utils.py:181-226).
// Here the FFmpeg C libraries (libavformat/libavcodec/libswscale) feed
// host-side RGB24 buffers directly: frames land in caller-provided numpy
// memory in batches, ready for one host→HBM transfer, with no per-frame
// Python or subprocess overhead.
//
// C ABI (consumed via ctypes from video_features_tpu/io/native.py):
//   vf_open(path)                  -> opaque handle (NULL on failure)
//   vf_props(h, &fps,&n,&w,&h)     -> stream properties (n may be estimated)
//   vf_read(h, out, max_frames)    -> decode ≤max_frames RGB24 frames into
//                                     out (HWC, w*h*3 bytes each); returns
//                                     #frames, 0 at EOF, <0 on error
//   vf_close(h)
//   vf_last_error()                -> static string for the last vf_open error

extern "C" {
#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/display.h>
#include <libavutil/imgutils.h>
#include <libavutil/opt.h>
#include <libswresample/swresample.h>
#include <libswscale/swscale.h>
}

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "yuv2rgb_cv2_tables.h"

namespace {
thread_local std::string g_last_error;

struct Decoder {
  AVFormatContext* fmt = nullptr;
  AVCodecContext* codec = nullptr;
  SwsContext* sws = nullptr;
  AVPacket* pkt = nullptr;
  AVFrame* frame = nullptr;
  int stream_index = -1;
  int width = 0;    // coded geometry (sws output)
  int height = 0;
  int rotation = 0;  // clockwise degrees to apply for display (0/90/180/270)
  // last colorspace details applied to `sws` (avoid per-frame re-derivation)
  AVColorSpace sws_colorspace = AVCOL_SPC_NB;
  AVColorRange sws_range = AVCOL_RANGE_NB;
  // last source pixel format the details were derived for: pointer
  // equality on `sws` cannot detect a context sws_getCachedContext
  // rebuilt at the SAME address after a mid-stream pix_fmt change, and
  // the J-format full-range inference depends on src_fmt too
  AVPixelFormat sws_src_fmt = AV_PIX_FMT_NONE;
  bool sws_details_warned = false;
  unsigned char* stage = nullptr;  // aligned sws_scale target (see emit_rgb)
  double fps = 0.0;
  long num_frames = 0;
  bool draining = false;
  bool done = false;

  // geometry after rotation — what the caller sees
  int out_width() const { return rotation % 180 ? height : width; }
  int out_height() const { return rotation % 180 ? width : height; }
};

void destroy(Decoder* d) {
  if (!d) return;
  if (d->stage) av_free(d->stage);
  if (d->sws) sws_freeContext(d->sws);
  if (d->frame) av_frame_free(&d->frame);
  if (d->pkt) av_packet_free(&d->pkt);
  if (d->codec) avcodec_free_context(&d->codec);
  if (d->fmt) avformat_close_input(&d->fmt);
  delete d;
}

bool fail(const std::string& msg) {
  g_last_error = msg;
  return false;
}

bool open_impl(Decoder* d, const char* path) {
  if (avformat_open_input(&d->fmt, path, nullptr, nullptr) < 0)
    return fail(std::string("cannot open ") + path);
  if (avformat_find_stream_info(d->fmt, nullptr) < 0)
    return fail("no stream info");
  const AVCodec* dec = nullptr;
  d->stream_index =
      av_find_best_stream(d->fmt, AVMEDIA_TYPE_VIDEO, -1, -1, &dec, 0);
  if (d->stream_index < 0 || !dec) return fail("no video stream");
  AVStream* st = d->fmt->streams[d->stream_index];

  d->codec = avcodec_alloc_context3(dec);
  if (!d->codec ||
      avcodec_parameters_to_context(d->codec, st->codecpar) < 0)
    return fail("codec context setup failed");
  d->codec->thread_count = 0;  // auto
  if (avcodec_open2(d->codec, dec, nullptr) < 0)
    return fail("cannot open codec");

  d->width = d->codec->width;
  d->height = d->codec->height;

  // Display-matrix rotation (portrait phone videos etc.). cv2 auto-rotates
  // since OpenCV 4.5; matching it keeps the native and cv2 backends
  // interchangeable. Same convention as ffmpeg's autorotate: theta is the
  // clockwise rotation to apply for correct display.
#if LIBAVFORMAT_VERSION_MAJOR >= 61
  // FFmpeg 7+: stream side data moved to codecpar->coded_side_data
  const AVPacketSideData* psd = av_packet_side_data_get(
      st->codecpar->coded_side_data, st->codecpar->nb_coded_side_data,
      AV_PKT_DATA_DISPLAYMATRIX);
  const uint8_t* sd = psd ? psd->data : nullptr;
#else
  const uint8_t* sd =
      av_stream_get_side_data(st, AV_PKT_DATA_DISPLAYMATRIX, nullptr);
#endif
  if (sd) {
    double theta = -av_display_rotation_get((const int32_t*)sd);
    theta -= 360.0 * std::floor(theta / 360.0 + 0.9 / 360.0);
    d->rotation = ((int)(theta / 90.0 + 0.5) % 4) * 90;
    if (d->rotation) {
      d->stage = (unsigned char*)av_malloc((size_t)3 * d->width * d->height);
      if (!d->stage) return fail("alloc failed");
    }
  }

  AVRational r = st->avg_frame_rate.num ? st->avg_frame_rate : st->r_frame_rate;
  d->fps = r.den ? av_q2d(r) : 0.0;
  d->num_frames = st->nb_frames;
  if (d->num_frames <= 0 && d->fmt->duration > 0 && d->fps > 0)
    d->num_frames =
        (long)(d->fmt->duration / (double)AV_TIME_BASE * d->fps + 0.5);

  d->pkt = av_packet_alloc();
  d->frame = av_frame_alloc();
  if (!d->pkt || !d->frame) return fail("alloc failed");
  return true;
}

// Lazily (re)build the RGB24 converter — pixel format can change mid-stream.
// ACCURATE_RND is REQUIRED for correctness, not a quality nicety: without
// it swscale picks SIMD paths per call based on source (frame-pool) and
// destination buffer alignment, both of which vary across allocations — so
// repeated decodes of the same file silently differed by a few levels in
// ~1% of pixels (measured; BITEXACT alone did NOT fix it). BITEXACT rides
// along to additionally pin dithering/rounding across CPU architectures.
// The accurate-rounding paths are alignment-independent and fully
// deterministic.
//
// This is the FALLBACK converter (everything the cv2-exact table path
// declines: tagged non-601 matrices, 10-bit, 4:2:2, full-range). It
// honors the frame's tagged colorspace/range via sws_setColorspaceDetails
// — a metadata-aware cv2 does the same, so e.g. BT.709-tagged HD content
// converts with 709 coefficients on both sides (within swscale-generation
// rounding, ~1 level), instead of silently using 601.
bool ensure_sws(Decoder* d, AVPixelFormat src_fmt) {
  SwsContext* prev = d->sws;
  d->sws = sws_getCachedContext(d->sws, d->width, d->height, src_fmt,
                                d->width, d->height, AV_PIX_FMT_RGB24,
                                SWS_BILINEAR | SWS_BITEXACT | SWS_ACCURATE_RND,
                                nullptr, nullptr, nullptr);
  if (!d->sws) return false;
  // Re-derive the coefficient tables only when the context was rebuilt or
  // the frame's tags changed — sws_setColorspaceDetails regenerates
  // yuv2rgb tables, which must not run per frame in the decode hot loop.
  // `src_fmt` participates in the staleness check because a mid-stream
  // pixel-format change makes sws_getCachedContext free + re-create the
  // context, and the fresh allocation can land at the SAME address —
  // pointer equality alone would then skip the re-derivation a brand-new
  // context needs (and the YUVJ* full-range inference below reads src_fmt
  // even when the colorspace/range tags are unchanged).
  if (d->sws == prev && src_fmt == d->sws_src_fmt &&
      d->frame->colorspace == d->sws_colorspace &&
      d->frame->color_range == d->sws_range)
    return true;
  d->sws_src_fmt = src_fmt;
  d->sws_colorspace = d->frame->colorspace;
  d->sws_range = d->frame->color_range;
  int cs = SWS_CS_ITU601;
  switch (d->frame->colorspace) {
    case AVCOL_SPC_BT709: cs = SWS_CS_ITU709; break;
    case AVCOL_SPC_SMPTE240M: cs = SWS_CS_SMPTE240M; break;
    case AVCOL_SPC_BT2020_NCL:
    case AVCOL_SPC_BT2020_CL: cs = SWS_CS_BT2020; break;
    default: break;
  }
  // Deprecated YUVJ* formats carry full range IN the format; honor it
  // when the frame's own range tag is unspecified (a remux can strip the
  // tag while the J format survives) — pre-round-5 handle_jpeg behavior.
  const bool j_fmt = src_fmt == AV_PIX_FMT_YUVJ420P ||
                     src_fmt == AV_PIX_FMT_YUVJ422P ||
                     src_fmt == AV_PIX_FMT_YUVJ444P ||
                     src_fmt == AV_PIX_FMT_YUVJ440P ||
                     src_fmt == AV_PIX_FMT_YUVJ411P;
  const int src_full =
      (d->frame->color_range == AVCOL_RANGE_JPEG ||
       (d->frame->color_range == AVCOL_RANGE_UNSPECIFIED && j_fmt)) ? 1 : 0;
  if (sws_setColorspaceDetails(d->sws, sws_getCoefficients(cs), src_full,
                               sws_getCoefficients(cs), 1 /* RGB full */,
                               0, 1 << 16, 1 << 16) < 0 &&
      !d->sws_details_warned) {
    // -1 = this converter path ignores details: conversion proceeds with
    // swscale defaults. Surface it once — a silently-601 tagged stream
    // is exactly the failure mode the colorspace handling exists to stop.
    fprintf(stderr,
            "vfdecode: sws_setColorspaceDetails unsupported for this "
            "format; converting with swscale defaults\n");
    d->sws_details_warned = true;
  }
  return true;
}

// Rotate an RGB24 image by d->rotation degrees clockwise: src is coded
// H×W, dst is the display geometry. Plain pixel loops; memory-bound, cheap
// relative to decode.
void rotate_rgb(const Decoder* d, const unsigned char* src,
                unsigned char* dst) {
  const int h = d->height, w = d->width;
  auto px = [&](int r, int c) { return src + 3 * ((size_t)r * w + c); };
  unsigned char* o = dst;
  if (d->rotation == 90) {  // dst (w × h): dst(r,c) = src(h-1-c, r)
    for (int r = 0; r < w; ++r)
      for (int c = 0; c < h; ++c, o += 3) std::memcpy(o, px(h - 1 - c, r), 3);
  } else if (d->rotation == 180) {
    for (int r = 0; r < h; ++r)
      for (int c = 0; c < w; ++c, o += 3)
        std::memcpy(o, px(h - 1 - r, w - 1 - c), 3);
  } else {  // 270: dst (w × h): dst(r,c) = src(c, w-1-r)
    for (int r = 0; r < w; ++r)
      for (int c = 0; c < h; ++c, o += 3) std::memcpy(o, px(c, w - 1 - r), 3);
  }
}

// cv2-exact yuv420p → RGB24: the integer-table arithmetic of cv2's
// bundled swscale, recovered bit-exactly by tools/fit_cv2_yuv_tables.py
// (see that tool's docstring for the method and verification). Nearest
// chroma (U,V at [r/2][c/2]), per-channel table sums, clip. Makes the
// native backend's pixels IDENTICAL to the reference's cv2 decode, which
// is what lets it be the default backend at the parity bar.
inline uint8_t clip8(int v) {
  return (uint8_t)(v < 0 ? 0 : (v > 255 ? 255 : v));
}

void yuv420_to_rgb_cv2(const AVFrame* f, int w, int h, unsigned char* out) {
  for (int r = 0; r < h; ++r) {
    const uint8_t* yrow = f->data[0] + (size_t)r * f->linesize[0];
    const uint8_t* urow = f->data[1] + (size_t)(r >> 1) * f->linesize[1];
    const uint8_t* vrow = f->data[2] + (size_t)(r >> 1) * f->linesize[2];
    unsigned char* o = out + (size_t)r * w * 3;
    for (int c = 0; c < w; ++c, o += 3) {
      const int y = yrow[c], u = urow[c >> 1], v = vrow[c >> 1];
      o[0] = clip8(kTY_R[y] + kTV_R[v]);
      o[1] = clip8(kTY_G[y] + kTU_G[u] + kTV_G[v]);
      o[2] = clip8(kTY_B[y] + kTU_B[u]);
    }
  }
}

// The table path covers exactly what the tables were fitted on: 8-bit
// 4:2:0, limited/unspecified range, BT.601-family (or untagged) matrix.
// Anything else — 10-bit, 4:2:2, full-range jpeg variants, or a clip
// whose VUI explicitly tags a non-601 matrix (BT.709 HD camera output,
// which a metadata-aware cv2 would convert with 709 coefficients) —
// goes through swscale: a documented approximation there, bit-exact-to-
// cv2 here.
bool use_cv2_tables(const Decoder* d) {
  const AVColorSpace cs = d->frame->colorspace;
  return d->frame->format == AV_PIX_FMT_YUV420P &&
         d->frame->color_range != AVCOL_RANGE_JPEG &&
         (cs == AVCOL_SPC_UNSPECIFIED || cs == AVCOL_SPC_BT470BG ||
          cs == AVCOL_SPC_SMPTE170M);
}

void emit_rgb(Decoder* d, unsigned char* out) {
  unsigned char* target = d->rotation ? d->stage : out;
  if (use_cv2_tables(d)) {
    yuv420_to_rgb_cv2(d->frame, d->width, d->height, target);
  } else {
    // rotation goes through the coded-geometry staging buffer; otherwise
    // convert straight into the caller's frame slot (safe: ACCURATE_RND
    // output does not depend on destination alignment)
    uint8_t* dst[1] = {target};
    int dst_linesize[1] = {3 * d->width};
    sws_scale(d->sws, d->frame->data, d->frame->linesize, 0, d->height, dst,
              dst_linesize);
  }
  if (d->rotation) rotate_rgb(d, d->stage, out);
}
}  // namespace

extern "C" {

void* vf_open(const char* path) {
  Decoder* d = new Decoder();
  if (!open_impl(d, path)) {
    destroy(d);
    return nullptr;
  }
  return d;
}

const char* vf_last_error() { return g_last_error.c_str(); }

void vf_props(void* handle, double* fps, long* num_frames, int* width,
              int* height) {
  Decoder* d = (Decoder*)handle;
  if (fps) *fps = d->fps;
  if (num_frames) *num_frames = d->num_frames;
  if (width) *width = d->out_width();
  if (height) *height = d->out_height();
}

// Clockwise display rotation applied to emitted frames (0/90/180/270).
int vf_rotation(void* handle) { return ((Decoder*)handle)->rotation; }

// The one receive/drain/send packet pump both read surfaces share:
// leaves the next decoded frame in d->frame and returns 1, or 0 at EOF
// (sets d->done), -2 on decode error, -3 on a mid-stream resolution
// change (the caller's buffer geometry would be stale). Caller must
// av_frame_unref when finished with the frame.
int next_frame(Decoder* d) {
  if (d->done) return 0;
  while (true) {
    int ret = avcodec_receive_frame(d->codec, d->frame);
    if (ret == 0) {
      if (d->frame->width != d->width || d->frame->height != d->height) {
        av_frame_unref(d->frame);
        return -3;
      }
      return 1;
    }
    if (ret == AVERROR_EOF) {
      d->done = true;
      return 0;
    }
    if (ret != AVERROR(EAGAIN)) return -2;

    // decoder wants input
    if (d->draining) continue;
    ret = av_read_frame(d->fmt, d->pkt);
    if (ret < 0) {
      avcodec_send_packet(d->codec, nullptr);  // start flush
      d->draining = true;
      continue;
    }
    if (d->pkt->stream_index == d->stream_index)
      avcodec_send_packet(d->codec, d->pkt);
    av_packet_unref(d->pkt);
  }
}

long vf_read(void* handle, unsigned char* out, long max_frames) {
  Decoder* d = (Decoder*)handle;
  if (max_frames <= 0) return 0;
  const long frame_bytes = 3L * d->width * d->height;
  long produced = 0;

  while (produced < max_frames) {
    int ret = next_frame(d);
    if (ret < 0) return ret;
    if (ret == 0) break;
    if (!use_cv2_tables(d) &&
        !ensure_sws(d, (AVPixelFormat)d->frame->format)) {
      av_frame_unref(d->frame);
      return -1;
    }
    emit_rgb(d, out + produced * frame_bytes);
    av_frame_unref(d->frame);
    ++produced;
  }
  return produced;
}

// Decode the next frame and expose its raw yuv420p planes (Y: H×W,
// U/V: H/2×W/2, no rotation applied). Diagnostic surface for pinning the
// YUV→RGB conversion stage against other decoders: the planes are what
// libavcodec produced, before any swscale processing. Returns 1 on
// success, 0 at EOF, <0 on error (-4: not yuv420p).
long vf_read_yuv(void* handle, unsigned char* y, unsigned char* u,
                 unsigned char* v) {
  Decoder* d = (Decoder*)handle;
  int ret = next_frame(d);
  if (ret <= 0) return ret;
  if (d->frame->format != AV_PIX_FMT_YUV420P &&
      d->frame->format != AV_PIX_FMT_YUVJ420P) {
    av_frame_unref(d->frame);
    return -4;
  }
  const int w = d->width, h = d->height;
  const int cw = (w + 1) / 2, ch = (h + 1) / 2;
  for (int r = 0; r < h; ++r)
    std::memcpy(y + (size_t)r * w,
                d->frame->data[0] + (size_t)r * d->frame->linesize[0], w);
  for (int r = 0; r < ch; ++r) {
    std::memcpy(u + (size_t)r * cw,
                d->frame->data[1] + (size_t)r * d->frame->linesize[1], cw);
    std::memcpy(v + (size_t)r * cw,
                d->frame->data[2] + (size_t)r * d->frame->linesize[2], cw);
  }
  av_frame_unref(d->frame);
  return 1;
}

void vf_close(void* handle) { destroy((Decoder*)handle); }

}  // extern "C"

// ---------------------------------------------------------------------------
// Audio: demux + decode + resample to mono float32 at a target rate.
//
// Replaces the reference's two-stage ffmpeg subprocess pipeline
// (mp4 → aac → wav, reference utils/utils.py:197-226) for hosts without an
// ffmpeg binary: the same libav libraries demux and decode in-process, and
// libswresample converts straight to the VGGish input format (mono float,
// 16 kHz) — no temp files, no int16 round-trip.

namespace {

struct AudioDecoder {
  AVFormatContext* fmt = nullptr;
  AVCodecContext* codec = nullptr;
  SwrContext* swr = nullptr;
  AVPacket* pkt = nullptr;
  AVFrame* frame = nullptr;
  int stream_index = -1;
  int out_rate = 0;
  std::vector<float> carry;  // resampled samples not yet taken by the caller
  size_t carry_pos = 0;
  bool draining = false;
  bool done = false;
};

void destroy_audio(AudioDecoder* d) {
  if (!d) return;
  if (d->swr) swr_free(&d->swr);
  if (d->frame) av_frame_free(&d->frame);
  if (d->pkt) av_packet_free(&d->pkt);
  if (d->codec) avcodec_free_context(&d->codec);
  if (d->fmt) avformat_close_input(&d->fmt);
  delete d;
}

bool open_audio_impl(AudioDecoder* d, const char* path, int target_rate) {
  if (avformat_open_input(&d->fmt, path, nullptr, nullptr) < 0)
    return fail(std::string("cannot open ") + path);
  if (avformat_find_stream_info(d->fmt, nullptr) < 0)
    return fail("no stream info");
  const AVCodec* dec = nullptr;
  d->stream_index =
      av_find_best_stream(d->fmt, AVMEDIA_TYPE_AUDIO, -1, -1, &dec, 0);
  if (d->stream_index < 0 || !dec) return fail("no audio stream");
  AVStream* st = d->fmt->streams[d->stream_index];

  d->codec = avcodec_alloc_context3(dec);
  if (!d->codec ||
      avcodec_parameters_to_context(d->codec, st->codecpar) < 0)
    return fail("audio codec context setup failed");
  if (avcodec_open2(d->codec, dec, nullptr) < 0)
    return fail("cannot open audio codec");

  d->out_rate = target_rate > 0 ? target_rate : d->codec->sample_rate;
  AVChannelLayout mono = AV_CHANNEL_LAYOUT_MONO;
  // must be zero-initialized: av_channel_layout_copy() uninit()s dst first,
  // and stack garbage that looks like AV_CHANNEL_ORDER_CUSTOM would free a
  // wild u.map pointer
  AVChannelLayout in_layout = {};
  if (d->codec->ch_layout.nb_channels > 0)
    av_channel_layout_copy(&in_layout, &d->codec->ch_layout);
  else
    av_channel_layout_default(&in_layout, 1);
  int ret = swr_alloc_set_opts2(&d->swr, &mono, AV_SAMPLE_FMT_FLT,
                                d->out_rate, &in_layout,
                                d->codec->sample_fmt, d->codec->sample_rate,
                                0, nullptr);
  av_channel_layout_uninit(&in_layout);
  if (ret < 0 || !d->swr || swr_init(d->swr) < 0)
    return fail("resampler setup failed");

  d->pkt = av_packet_alloc();
  d->frame = av_frame_alloc();
  if (!d->pkt || !d->frame) return fail("alloc failed");
  return true;
}

// Convert one decoded frame (or flush with null) through swr into carry.
bool push_resampled(AudioDecoder* d, const AVFrame* in) {
  const uint8_t** src = in ? (const uint8_t**)in->extended_data : nullptr;
  int in_count = in ? in->nb_samples : 0;
  int64_t delay = swr_get_delay(d->swr, d->codec->sample_rate) + in_count;
  int max_out = (int)av_rescale_rnd(delay, d->out_rate,
                                    d->codec->sample_rate, AV_ROUND_UP) + 32;
  size_t old = d->carry.size();
  d->carry.resize(old + max_out);
  uint8_t* dst[1] = {(uint8_t*)(d->carry.data() + old)};
  int got = swr_convert(d->swr, dst, max_out, src, in_count);
  if (got < 0) return false;
  d->carry.resize(old + got);
  return true;
}

}  // namespace

extern "C" {

void* vf_audio_open(const char* path, int target_rate) {
  AudioDecoder* d = new AudioDecoder();
  if (!open_audio_impl(d, path, target_rate)) {
    destroy_audio(d);
    return nullptr;
  }
  return d;
}

int vf_audio_rate(void* handle) { return ((AudioDecoder*)handle)->out_rate; }

// Decode ≤ max_samples mono float32 samples into out. Returns the number
// produced, 0 at EOF, <0 on error.
long vf_audio_read(void* handle, float* out, long max_samples) {
  AudioDecoder* d = (AudioDecoder*)handle;
  if (max_samples <= 0) return 0;
  long produced = 0;

  while (produced < max_samples) {
    // serve buffered samples first
    size_t avail = d->carry.size() - d->carry_pos;
    if (avail > 0) {
      size_t take = std::min<size_t>(avail, max_samples - produced);
      std::memcpy(out + produced, d->carry.data() + d->carry_pos,
                  take * sizeof(float));
      d->carry_pos += take;
      produced += (long)take;
      if (d->carry_pos == d->carry.size()) {
        d->carry.clear();
        d->carry_pos = 0;
      }
      continue;
    }
    if (d->done) break;

    int ret = avcodec_receive_frame(d->codec, d->frame);
    if (ret == 0) {
      bool ok = push_resampled(d, d->frame);
      av_frame_unref(d->frame);
      if (!ok) return -1;
      continue;
    }
    if (ret == AVERROR_EOF) {
      if (!push_resampled(d, nullptr)) return -1;  // flush the resampler
      d->done = true;
      continue;
    }
    if (ret != AVERROR(EAGAIN)) return -2;

    if (d->draining) continue;
    ret = av_read_frame(d->fmt, d->pkt);
    if (ret < 0) {
      avcodec_send_packet(d->codec, nullptr);
      d->draining = true;
      continue;
    }
    if (d->pkt->stream_index == d->stream_index)
      avcodec_send_packet(d->codec, d->pkt);
    av_packet_unref(d->pkt);
  }
  return produced;
}

void vf_audio_close(void* handle) { destroy_audio((AudioDecoder*)handle); }

}  // extern "C"

// ---------------------------------------------------------------------------
// CFR re-encode: the reference's `ffmpeg -i in -filter:v fps=fps=N out.mp4`
// (reference utils/io.py:14-36) without the ffmpeg binary.
//
// Replicates the two pieces that define the output pixels:
//   * the fps filter (libavfilter vf_fps.c, round=near, eof_action=round):
//     input pts are rescaled to the 1/N output timebase with near
//     rounding; each output slot shows the latest input frame at or
//     before it (zero-order hold with duplicate/drop);
//   * the ffmpeg CLI's libx264 defaults (crf 23, encoder-default preset
//     'medium', auto threads) on the DECODED YUV frames — the CLI invokes
//     no pixel-format conversion when the input is already yuv420p.
//
// C ABI:
//   vf_reencode_fps(in, out, fps) -> 0 ok, <0 error (vf_last_error()).

namespace {

struct Reencoder {
  AVFormatContext* in_fmt = nullptr;
  AVCodecContext* dec = nullptr;
  AVFormatContext* out_fmt = nullptr;
  AVCodecContext* enc = nullptr;
  AVStream* out_stream = nullptr;
  AVPacket* pkt = nullptr;
  AVPacket* out_pkt = nullptr;
  AVFrame* frame = nullptr;
  AVFrame* held = nullptr;     // fps-filter zero-order-hold frame
  int stream_index = -1;
  int64_t next_pts = AV_NOPTS_VALUE;  // next output slot (out timebase)
  int64_t last_in_pts = AV_NOPTS_VALUE;  // last input frame (in timebase)
  int64_t prev_in_pts = AV_NOPTS_VALUE;  // the one before it
  int64_t last_in_dur = 0;
  AVRational in_tb{};
  AVRational out_tb{};
};

void destroy_reenc(Reencoder* r) {
  if (!r) return;
  if (r->held) av_frame_free(&r->held);
  if (r->frame) av_frame_free(&r->frame);
  if (r->pkt) av_packet_free(&r->pkt);
  if (r->out_pkt) av_packet_free(&r->out_pkt);
  if (r->enc) avcodec_free_context(&r->enc);
  if (r->out_fmt) {
    if (!(r->out_fmt->oformat->flags & AVFMT_NOFILE) && r->out_fmt->pb)
      avio_closep(&r->out_fmt->pb);
    avformat_free_context(r->out_fmt);
  }
  if (r->dec) avcodec_free_context(&r->dec);
  if (r->in_fmt) avformat_close_input(&r->in_fmt);
  delete r;
}

int fail_i(const std::string& msg) {
  g_last_error = msg;
  return -1;
}

// Drain encoder packets into the muxer.
int mux_pending(Reencoder* r) {
  while (true) {
    int ret = avcodec_receive_packet(r->enc, r->out_pkt);
    if (ret == AVERROR(EAGAIN) || ret == AVERROR_EOF) return 0;
    if (ret < 0) return fail_i("encode failed");
    av_packet_rescale_ts(r->out_pkt, r->enc->time_base,
                         r->out_stream->time_base);
    r->out_pkt->stream_index = r->out_stream->index;
    if (av_interleaved_write_frame(r->out_fmt, r->out_pkt) < 0)
      return fail_i("mux write failed");
  }
}

// Emit the held frame once per output slot strictly before `until`.
int emit_until(Reencoder* r, int64_t until) {
  while (r->next_pts < until) {
    r->held->pts = r->next_pts++;
    r->held->pict_type = AV_PICTURE_TYPE_NONE;  // encoder decides
    if (getenv("VF_REENC_DEBUG")) {
      unsigned long sum = 0;
      for (int p = 0; p < 3; ++p) {
        int ph = p ? r->enc->height / 2 : r->enc->height;
        int pw = p ? r->enc->width / 2 : r->enc->width;
        for (int y = 0; y < ph; ++y)
          for (int x = 0; x < pw; ++x)
            sum = sum * 31 + r->held->data[p][y * r->held->linesize[p] + x];
      }
      fprintf(stderr, "[reenc] slot %ld yuvhash %lx\n",
              (long)r->held->pts, sum);
    }
    int ret = avcodec_send_frame(r->enc, r->held);
    if (ret < 0) return fail_i("encoder rejected frame");
    if (mux_pending(r) < 0) return -1;
  }
  return 0;
}

// One decoded frame enters the fps filter: rescale its pts to the output
// timebase (near rounding — vf_fps.c), flush slots owed to the held
// frame, then hold this one (dropping the old if it never owned a slot).
int fps_push(Reencoder* r, AVFrame* f) {
  int64_t pts_out = av_rescale_q_rnd(
      f->best_effort_timestamp, r->in_tb, r->out_tb,
      (AVRounding)(AV_ROUND_NEAR_INF | AV_ROUND_PASS_MINMAX));
  if (r->next_pts == AV_NOPTS_VALUE) r->next_pts = pts_out;
  if (r->held && emit_until(r, pts_out) < 0) return -1;
  if (!r->held) r->held = av_frame_alloc();
  av_frame_unref(r->held);
  if (av_frame_ref(r->held, f) < 0) return fail_i("frame ref failed");
  r->held->pts = pts_out;
  r->prev_in_pts = r->last_in_pts;
  r->last_in_pts = f->best_effort_timestamp;
#if LIBAVUTIL_VERSION_MAJOR >= 58
  r->last_in_dur = f->duration;   // FFmpeg 6+
#else
  r->last_in_dur = f->pkt_duration;
#endif
  return 0;
}

int open_reencoder(Reencoder* r, const char* in_path, const char* out_path,
                   AVRational fps) {
  if (avformat_open_input(&r->in_fmt, in_path, nullptr, nullptr) < 0)
    return fail_i(std::string("cannot open ") + in_path);
  if (avformat_find_stream_info(r->in_fmt, nullptr) < 0)
    return fail_i("no stream info");
  const AVCodec* dec_codec = nullptr;
  r->stream_index = av_find_best_stream(r->in_fmt, AVMEDIA_TYPE_VIDEO, -1,
                                        -1, &dec_codec, 0);
  if (r->stream_index < 0 || !dec_codec) return fail_i("no video stream");
  AVStream* ist = r->in_fmt->streams[r->stream_index];
  r->dec = avcodec_alloc_context3(dec_codec);
  if (!r->dec ||
      avcodec_parameters_to_context(r->dec, ist->codecpar) < 0)
    return fail_i("decoder setup failed");
  r->dec->thread_count = 0;
  if (avcodec_open2(r->dec, dec_codec, nullptr) < 0)
    return fail_i("cannot open decoder");
  r->in_tb = ist->time_base;
  r->out_tb = av_inv_q(fps);

  const AVCodec* enc_codec = avcodec_find_encoder_by_name("libx264");
  if (!enc_codec) return fail_i("libx264 encoder not available");
  if (avformat_alloc_output_context2(&r->out_fmt, nullptr, nullptr,
                                     out_path) < 0 || !r->out_fmt)
    return fail_i("cannot create output context");
  r->enc = avcodec_alloc_context3(enc_codec);
  if (!r->enc) return fail_i("encoder alloc failed");
  r->enc->width = r->dec->width;
  r->enc->height = r->dec->height;
  r->enc->sample_aspect_ratio = r->dec->sample_aspect_ratio;
  // the CLI inserts no format filter for yuv420p input; yuvj420p maps to
  // yuv420p + color_range copy
  AVPixelFormat pix = r->dec->pix_fmt;
  if (pix == AV_PIX_FMT_YUVJ420P) pix = AV_PIX_FMT_YUV420P;
  if (pix != AV_PIX_FMT_YUV420P)
    return fail_i("reencode supports yuv420p input only");
  r->enc->pix_fmt = pix;
  r->enc->color_range = r->dec->color_range;
  r->enc->color_primaries = r->dec->color_primaries;
  r->enc->color_trc = r->dec->color_trc;
  r->enc->colorspace = r->dec->colorspace;
  r->enc->time_base = r->out_tb;
  r->enc->framerate = fps;
  r->enc->thread_count = 0;  // auto, like the CLI
  if (r->out_fmt->oformat->flags & AVFMT_GLOBALHEADER)
    r->enc->flags |= AV_CODEC_FLAG_GLOBAL_HEADER;
  // ffmpeg CLI default for libx264: crf 23 (preset stays the wrapper's
  // default 'medium')
  av_opt_set(r->enc->priv_data, "crf", "23", 0);
  if (avcodec_open2(r->enc, enc_codec, nullptr) < 0)
    return fail_i("cannot open libx264");

  r->out_stream = avformat_new_stream(r->out_fmt, nullptr);
  if (!r->out_stream) return fail_i("cannot create output stream");
  if (avcodec_parameters_from_context(r->out_stream->codecpar, r->enc) < 0)
    return fail_i("stream params failed");
  r->out_stream->time_base = r->enc->time_base;
  r->out_stream->avg_frame_rate = fps;
  if (!(r->out_fmt->oformat->flags & AVFMT_NOFILE) &&
      avio_open(&r->out_fmt->pb, out_path, AVIO_FLAG_WRITE) < 0)
    return fail_i(std::string("cannot open for write: ") + out_path);
  if (avformat_write_header(r->out_fmt, nullptr) < 0)
    return fail_i("cannot write header");

  r->pkt = av_packet_alloc();
  r->out_pkt = av_packet_alloc();
  r->frame = av_frame_alloc();
  if (!r->pkt || !r->out_pkt || !r->frame) return fail_i("alloc failed");
  return 0;
}

int run_reencode(Reencoder* r) {
  bool draining = false;
  while (true) {
    int ret = avcodec_receive_frame(r->dec, r->frame);
    if (ret == 0) {
      if (fps_push(r, r->frame) < 0) return -1;
      av_frame_unref(r->frame);
      continue;
    }
    if (ret == AVERROR_EOF) break;
    if (ret != AVERROR(EAGAIN)) return fail_i("decode failed");
    if (draining) continue;
    ret = av_read_frame(r->in_fmt, r->pkt);
    if (ret < 0) {
      avcodec_send_packet(r->dec, nullptr);
      draining = true;
      continue;
    }
    if (r->pkt->stream_index == r->stream_index)
      avcodec_send_packet(r->dec, r->pkt);
    av_packet_unref(r->pkt);
  }
  // EOF flush (eof_action=round): the held frame owns every slot strictly
  // before the stream's end time (last frame pts + its duration, rescaled
  // with near rounding) — round(duration·N) total frames for CFR input.
  if (r->held && r->next_pts != AV_NOPTS_VALUE) {
    // last frame's display interval: its own duration when known, else
    // one decoder frame interval, else the last observed pts delta;
    // with none of those (single frame, no metadata) grant it one slot.
    int64_t dur = r->last_in_dur;
    if (dur <= 0 && r->dec->framerate.num > 0)
      dur = av_rescale_q(1, av_inv_q(r->dec->framerate), r->in_tb);
    if (dur <= 0 && r->prev_in_pts != AV_NOPTS_VALUE)
      dur = r->last_in_pts - r->prev_in_pts;
    int64_t end_out;
    if (dur > 0) {
      end_out = av_rescale_q_rnd(
          r->last_in_pts + dur, r->in_tb, r->out_tb,
          (AVRounding)(AV_ROUND_NEAR_INF | AV_ROUND_PASS_MINMAX));
      // a held frame whose slot lies at/after the end time is dropped,
      // exactly like the filter (timing wins over content at the tail)
    } else {
      end_out = r->held->pts + 1;
    }
    if (emit_until(r, end_out) < 0) return -1;
  }
  if (avcodec_send_frame(r->enc, nullptr) < 0)  // flush encoder
    return fail_i("encoder flush failed");
  if (mux_pending(r) < 0) return -1;
  if (av_write_trailer(r->out_fmt) < 0) return fail_i("trailer failed");
  return 0;
}

}  // namespace

extern "C" {

int vf_reencode_fps(const char* in_path, const char* out_path, double fps) {
  if (fps <= 0) return fail_i("fps must be positive");
  // mirror the reference CLI's `-hide_banner -loglevel panic` (its ffmpeg
  // invocation is silent; x264's per-encode stats would spam every video);
  // VF_REENC_DEBUG=1 restores full logs for debugging
  av_log_set_level(getenv("VF_REENC_DEBUG") ? AV_LOG_DEBUG : AV_LOG_ERROR);
  // Pin the SSE FP environment for the encode (defense in depth; restore
  // after). NOTE: x264's rate control was measured to make stably
  // different decisions for IDENTICAL input frames depending on
  // process-global state (flipped by XLA:CPU jit initialization in the
  // same process; encoder-input YUV hashes and the x264 options banner
  // identical, MXCSR unchanged — the mechanism is inside x264). Callers
  // who need byte-deterministic output must run this function in a fresh
  // process — io/reencode_cli.py, the production path — which matches
  // the reference's ffmpeg-CLI execution model.
#if defined(__SSE2__) || defined(__x86_64__)
  unsigned int saved_csr = __builtin_ia32_stmxcsr();
  __builtin_ia32_ldmxcsr(0x1f80);  // x86 default: no FTZ/DAZ, all masked
#endif
  Reencoder* r = new Reencoder();
  AVRational rate = av_d2q(fps, 100000);
  int ret = open_reencoder(r, in_path, out_path, rate);
  if (ret == 0) ret = run_reencode(r);
  destroy_reenc(r);
#if defined(__SSE2__) || defined(__x86_64__)
  __builtin_ia32_ldmxcsr(saved_csr);
#endif
  return ret;
}

}  // extern "C"
