"""vft-wire: static wire-contract checker over the serving API surface.

vft-lint (checks.py) pins the Python-level contracts and vft-programs
(programs.py) pins the compiled programs; the third contract surface is
the **wire** — what a client process on another host can say to this
one, and what it gets back. That surface is exactly what a mixed-version
fleet (ROADMAP item 3: N backend hosts behind an ingress tier, rolled
independently) depends on, and until now nothing pinned it: the
reference fork's defining bug was a contract silently broken at a seam,
and PR 8/PR 11 both extended the wire (``check_version``/``v``
stamping, the ``trace`` command + route) without touching
``protocol.VERSION`` — invisible drift this module now catches.

Pure AST, never imports the code it checks (and never jax — the same
subprocess discipline as vft-lint, same ``analysis/core.py`` exit-code
contract). It walks:

  * ``serve/protocol.py`` — VERSION / MAJOR, the ``CMD_*`` command
    vocabulary, ``SUBMIT_FIELDS``, ``PRIORITIES``;
  * ``serve/server.py`` ``_dispatch`` — every handled command with the
    request fields it reads and the response/error fields it writes
    (one hop into the ``self.<handler>`` methods, ``**snapshot()``
    resolved statically);
  * ``serve/client.py`` — every ``ServeClient`` method and the command
    + fields it sends;
  * ``ingress/gateway.py`` / ``http.py`` / ``live.py`` — every HTTP
    route (method, path pattern, auth requirement, tenant scoping,
    status codes, request/response fields, structured-error
    ``(status, code)`` shapes), the transport-level status vocabulary,
    and the ``vft_ingress_*`` metric families with their label sets.

The extracted surface is pinned in a committed ``WIRE.lock.json``; the
diff enforces **compatibility semantics**, not just drift: a removed or
renamed field/command/route/status code is a BREAKING change demanding
a MAJOR bump of ``protocol.VERSION``; additive changes re-pin under a
MINOR bump. Cross-layer sync rules ride along: a ``ServeClient`` method
with no server handler (or vice versa), a submit field the server would
reject, a structured error missing its ``request_id``/``tenant`` echo,
and a route or command missing from the ``docs/ingress.md`` /
``docs/serving.md`` tables are all findings.

Status codes and command names must be spelled via the shared constants
(``ingress/http.py``, ``serve/protocol.py`` ``CMD_*``) — vft-lint's
``wire-literal`` rule enforces that, which is what makes this
extraction sound: an inline literal would be invisible to it.

Suppression: ``# vft-wire: ok=<rule> — rationale`` on the finding's
line or the comment block above it (lock-drift findings are not
suppressible — re-pinning with ``--write-lock`` is the mechanism).
"""
from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from video_features_tpu.analysis.core import (
    EXIT_CLEAN, EXIT_ERROR, EXIT_FINDINGS, EXIT_IMPURE, INGRESS_GATEWAY_PY,
    INGRESS_HTTP_PY, SERVE_CLIENT_PY, SERVE_PROTOCOL_PY, SERVE_SERVER_PY,
    Finding, Module, Package, assigned_dict_keys, callable_name,
    find_assignment, find_function, module_constants, set_literal_values,
)

LOCK_SCHEMA = 'video_features_tpu.wire_lock/1'
DEFAULT_LOCK = 'WIRE.lock.json'               # repo-root, committed

INGRESS_LIVE_PY = 'ingress/live.py'

RULES = ('wire-sync', 'error-echo', 'doc-sync', 'lock-drift')

# the independently re-pinnable lock sections (--scope): a subset
# --write-lock merges only the named sections; the full-scope default
# rebuilds the document (pruning stale sections/keys)
SCOPES = ('commands', 'routes', 'transport', 'metrics')

# call positions whose first argument is an HTTP status code (the same
# vocabulary the wire-literal lint rule guards)
_STATUS_CALLS = ('HttpError', 'send_json', 'send', 'start_chunked')

_SUPPRESS_RE = re.compile(r'#\s*vft-wire:\s*ok=([a-z0-9_,-]+)')

# doc tables spell routes as `GET /path` / `POST /path` in backticks
_DOC_ROUTE_RE = re.compile(r'`(?:GET|POST)\s+(/[^`\s|]*)`')


# -- small AST helpers --------------------------------------------------------

def _resolve(node: ast.AST, consts: Dict[str, Any]) -> Any:
    """Constant / Name / Attribute → value via the constant table."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.Attribute):
        return consts.get(node.attr)
    return None


def _resolve_statuses(node: ast.AST, consts: Dict[str, Any]) -> List[int]:
    """Every status an expression can evaluate to (IfExp → both arms)."""
    if isinstance(node, ast.IfExp):
        return (_resolve_statuses(node.body, consts)
                + _resolve_statuses(node.orelse, consts))
    v = _resolve(node, consts)
    return [v] if isinstance(v, int) else []


def _resolve_seq(node: Optional[ast.AST],
                 consts: Dict[str, Any]) -> List[str]:
    """Resolved string members of a tuple/list literal whose elements
    may be constants or references to the module's own constants."""
    out: List[str] = []
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            v = _resolve(el, consts)
            if isinstance(v, str):
                out.append(v)
    return out


def _owning_class(tree: ast.AST, fn_name: str) -> Optional[ast.AST]:
    """The ClassDef whose body (directly) holds ``fn_name`` — method
    lookup must scope to it, or a same-named method on an unrelated
    class in the module wins the resolution."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
                isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef))
                and b.name == fn_name for b in node.body):
            return node
    return None


def _self_call_closure(scope: ast.AST,
                       roots: Iterable[ast.AST]) -> List[ast.AST]:
    """Every function in ``scope`` reachable from ``roots`` through
    ``self.<name>(...)`` calls, transitively — the static scope a
    handler's statuses/fields/errors can come from."""
    fns: List[ast.AST] = []
    seen: Set[str] = set()
    frontier = list(roots)
    while frontier:
        root = frontier.pop()
        for call in ast.walk(root):
            if isinstance(call, ast.Call) \
                    and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == 'self' \
                    and call.func.attr not in seen:
                seen.add(call.func.attr)
                fn = find_function(scope, call.func.attr)
                if fn is not None:
                    fns.append(fn)
                    frontier.append(fn)
    return fns


def _suppressed(mod: Optional[Module], rule: str, line: int) -> bool:
    """``# vft-wire: ok=<rule>`` on the line or the contiguous comment
    block above it (the vft-lint convention, wire's own marker)."""
    if mod is None:
        return False
    lines = mod.lines

    def at(ln: int) -> bool:
        if 1 <= ln <= len(lines):
            m = _SUPPRESS_RE.search(lines[ln - 1])
            return bool(m and rule in m.group(1).split(','))
        return False

    if at(line):
        return True
    ln = line - 1
    while ln >= 1 and lines[ln - 1].lstrip().startswith('#'):
        if at(ln):
            return True
        ln -= 1
    return False


# -- loopback protocol --------------------------------------------------------

def extract_protocol(pkg: Package) -> Dict[str, Any]:
    """VERSION/MAJOR + the declared command/field vocabularies."""
    mod = pkg.get(SERVE_PROTOCOL_PY)
    if mod is None:
        return {}
    consts = module_constants(mod)
    cmds_node = find_assignment(mod.tree, 'COMMANDS')
    return {
        'consts': consts,
        'version': consts.get('VERSION'),
        'major': consts.get('MAJOR'),
        'commands': _resolve_seq(cmds_node, consts),
        'commands_line': getattr(cmds_node, 'lineno', 1),
        'submit_fields': _resolve_seq(
            find_assignment(mod.tree, 'SUBMIT_FIELDS'), consts),
        'priorities': _resolve_seq(
            find_assignment(mod.tree, 'PRIORITIES'), consts),
    }


def _ok_error_fields(nodes: Iterable[ast.AST],
                     tree: ast.AST) -> Tuple[Set[str], Set[str]]:
    """Response/error field names written via ``protocol.ok(...)`` /
    ``protocol.error(...)`` across ``nodes``. ``**x.snapshot()`` spreads
    resolve against ``def snapshot`` in ``tree`` (the ``out`` dict)."""
    ok_fields: Set[str] = set()
    err_fields: Set[str] = set()
    for root in nodes:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            name = callable_name(node.func)
            if name not in ('ok', 'error'):
                continue
            if name == 'ok':
                dest = ok_fields
                dest.add('ok')
            else:
                dest = err_fields
                dest |= {'ok', 'error'}
            for kw in node.keywords:
                if kw.arg is not None:
                    dest.add(kw.arg)
                elif isinstance(kw.value, ast.Call) \
                        and callable_name(kw.value.func) == 'snapshot':
                    fn = find_function(tree, 'snapshot')
                    if fn is not None:
                        dest |= assigned_dict_keys(fn, 'out')
    return ok_fields, err_fields


def extract_server_commands(pkg: Package,
                            proto: Dict[str, Any]) -> Dict[str, Any]:
    """Every command ``_dispatch`` handles: request fields read off the
    message, response/error fields written (one hop into the
    ``self.<handler>`` methods called from the branch)."""
    mod = pkg.get(SERVE_SERVER_PY)
    if mod is None:
        return {}
    dispatch = find_function(mod.tree, '_dispatch')
    if dispatch is None:
        return {}
    consts = proto.get('consts', {})
    scope = _owning_class(mod.tree, '_dispatch') or mod.tree
    out: Dict[str, Any] = {}
    for node in ast.walk(dispatch):
        if not isinstance(node, ast.If):
            continue
        cmd = _cmd_of_test(node.test, consts)
        if cmd is None:
            continue
        # the branch plus everything it reaches via self.<handler>()
        # calls, scoped to the dispatching class (a same-named method
        # on another class in the file must not win the lookup)
        scan: List[ast.AST] = list(node.body) \
            + _self_call_closure(scope, node.body)
        req_fields: Set[str] = set()
        uses_submit_fields = False
        for root in node.body:
            for sub in ast.walk(root):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == 'get' \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == 'msg' \
                        and sub.args \
                        and isinstance(sub.args[0], ast.Constant):
                    req_fields.add(sub.args[0].value)
                if isinstance(sub, (ast.Name, ast.Attribute)):
                    ident = sub.id if isinstance(sub, ast.Name) else sub.attr
                    if ident == 'SUBMIT_FIELDS':
                        uses_submit_fields = True
        if uses_submit_fields:
            req_fields |= set(proto.get('submit_fields', ()))
        req_fields.discard('cmd')
        ok_fields, err_fields = _ok_error_fields(scan, mod.tree)
        out[cmd] = {
            'line': node.lineno,
            'request_fields': sorted(req_fields),
            'response_fields': sorted(ok_fields),
            'error_fields': sorted(err_fields),
        }
    return out


def _cmd_of_test(test: ast.AST, consts: Dict[str, Any]) -> Optional[str]:
    """``cmd == <CMD_* | 'literal'>`` comparison → the command name."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        return None
    sides = [test.left, test.comparators[0]]
    idents = {s.id for s in sides if isinstance(s, ast.Name)}
    idents |= {s.attr for s in sides if isinstance(s, ast.Attribute)
               if s.attr not in consts}
    if 'cmd' not in idents:
        return None
    for s in sides:
        v = _resolve(s, consts)
        if isinstance(v, str):
            return v
    return None


def extract_client(pkg: Package,
                   proto: Dict[str, Any]) -> Dict[str, Any]:
    """``ServeClient`` surface: command → the methods that speak it and
    the request fields they set (dict-literal keys + subscript assigns
    on the message variable + ``_call``-level ``setdefault`` fields)."""
    mod = pkg.get(SERVE_CLIENT_PY)
    if mod is None:
        return {}
    consts = proto.get('consts', {})
    common: Set[str] = set()
    call_fn = find_function(mod.tree, '_call')
    if call_fn is not None:
        for node in ast.walk(call_fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == 'setdefault' \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant):
                common.add(node.args[0].value)
    out: Dict[str, Any] = {}
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name.startswith('_'):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Dict):
                    continue
                cmd = None
                fields: Set[str] = set()
                for k, v in zip(node.keys, node.values):
                    if not isinstance(k, ast.Constant):
                        continue
                    fields.add(k.value)
                    if k.value == 'cmd':
                        cmd = _resolve(v, consts)
                if cmd is None:
                    continue
                # subscript assigns on the variable the dict landed in
                for stmt in ast.walk(fn):
                    target = None
                    if isinstance(stmt, ast.Assign) \
                            and stmt.value is node:
                        target = stmt.targets[0]
                    elif isinstance(stmt, ast.AnnAssign) \
                            and stmt.value is node:
                        target = stmt.target
                    if isinstance(target, ast.Name):
                        fields |= assigned_dict_keys(fn, target.id)
                entry = out.setdefault(
                    cmd, {'client_methods': [], 'fields': set(),
                          'line': node.lineno})
                if fn.name not in entry['client_methods']:
                    entry['client_methods'].append(fn.name)
                entry['fields'] |= fields | common
    for entry in out.values():
        entry['client_methods'].sort()
        entry['fields'] = sorted(entry['fields'])
    return out


# -- ingress routes -----------------------------------------------------------

def _route_conditions(test: ast.AST) -> Dict[str, str]:
    """Parse one route test into ``{eq|prefix|suffix|method: literal}``.
    Conditions anchor on the ``path``/``method`` locals (or
    ``req.path``/``req.method`` attributes)."""
    conds: Dict[str, str] = {}
    parts = test.values if isinstance(test, ast.BoolOp) else [test]
    for part in parts:
        if isinstance(part, ast.Compare) and len(part.ops) == 1 \
                and isinstance(part.ops[0], ast.Eq):
            sides = [part.left, part.comparators[0]]
            names = {s.id for s in sides if isinstance(s, ast.Name)}
            names |= {s.attr for s in sides
                      if isinstance(s, ast.Attribute)}
            lit = next((s.value for s in sides
                        if isinstance(s, ast.Constant)
                        and isinstance(s.value, str)), None)
            if lit is None:
                continue
            if 'path' in names:
                conds['eq'] = lit
            elif 'method' in names:
                conds['method'] = lit
        elif isinstance(part, ast.Call) \
                and isinstance(part.func, ast.Attribute) \
                and part.func.attr in ('startswith', 'endswith') \
                and part.args and isinstance(part.args[0], ast.Constant):
            base = part.func.value
            ident = base.id if isinstance(base, ast.Name) else \
                base.attr if isinstance(base, ast.Attribute) else ''
            if ident == 'path':
                key = 'prefix' if part.func.attr == 'startswith' \
                    else 'suffix'
                conds[key] = part.args[0].value
    return conds


def _route_pattern(conds: Dict[str, str]) -> Optional[str]:
    if 'eq' in conds:
        return conds['eq']
    if 'prefix' in conds:
        return conds['prefix'] + '<id>' + conds.get('suffix', '')
    return None


def _scan_route(pkg: Package, gw: Module, branch: List[ast.stmt],
                status_consts: Dict[str, Any]) -> Dict[str, Any]:
    """One route branch's surface: statuses, errors, fields, scoping —
    the branch body plus one hop into ``self.<helper>`` methods."""
    handler_fns = _self_call_closure(gw.tree, branch)
    scan: List[ast.AST] = list(branch) + handler_fns
    statuses: Set[int] = set()
    errors: Set[Tuple[int, str]] = set()
    resp_fields: Set[str] = set()
    req_fields: Set[str] = set()
    tenant_scoped = False
    uses_live = False
    for root in scan:
        for node in ast.walk(root):
            # tenant scoping = an owner LOOKUP (self._owners.get(...)):
            # routes that merely record ownership (_own's writes) serve
            # any authenticated tenant and are not scoped reads
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == 'get' \
                    and isinstance(node.func.value, ast.Attribute) \
                    and node.func.value.attr == '_owners':
                tenant_scoped = True
            if isinstance(node, ast.Name) and node.id == 'LiveSession':
                uses_live = True
            if isinstance(node, ast.Name) \
                    and node.id.endswith('_FIELDS'):
                fields_node = find_assignment(gw.tree, node.id)
                if fields_node is not None:
                    req_fields |= set_literal_values(fields_node)
            if not isinstance(node, ast.Call):
                continue
            name = callable_name(node.func)
            if name in _STATUS_CALLS and node.args:
                got = _resolve_statuses(node.args[0], status_consts)
                statuses.update(got)
                if name == 'HttpError' and len(node.args) >= 2 \
                        and isinstance(node.args[1], ast.Constant):
                    for st in got:
                        errors.add((st, node.args[1].value))
                if name == 'send_json' and len(node.args) >= 2:
                    resp_fields |= _dict_fields(node.args[1], root)
            if name == 'dumps' and node.args:
                resp_fields |= _dict_fields(node.args[0], root)
    if uses_live:
        live = pkg.get(INGRESS_LIVE_PY)
        if live is not None:
            fn = find_function(live.tree, 'send_window')
            if fn is not None:
                resp_fields |= assigned_dict_keys(fn, 'row')
    return {
        'status': sorted(statuses),
        'errors': [list(e) for e in sorted(errors)],
        'request_fields': sorted(req_fields),
        'response_fields': sorted(resp_fields),
        'tenant_scoped': tenant_scoped,
        'handlers': handler_fns,
    }


def _dict_fields(node: ast.AST, scope: ast.AST) -> Set[str]:
    """Statically visible keys of a response payload: literal dict keys
    plus (for ``**var`` spreads / ``dumps(var)``) the keys ``var`` is
    assigned within ``scope``."""
    fields: Set[str] = set()
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                fields.add(k.value)
            elif k is None and isinstance(v, ast.Name):
                fields |= assigned_dict_keys(scope, v.id)
    elif isinstance(node, ast.Name):
        fields |= assigned_dict_keys(scope, node.id)
    return fields


def extract_routes(pkg: Package) -> Tuple[Dict[str, Any], Set[int]]:
    """Every HTTP route off ``_handle`` (pre-auth) + ``_route`` (authed)
    → its pinned surface; plus the transport-level statuses emitted
    outside any route branch (auth gate, unknown-route fallback)."""
    gw = pkg.get(INGRESS_GATEWAY_PY)
    if gw is None:
        return {}, set()
    status_consts = module_constants(pkg.get(INGRESS_HTTP_PY))
    status_consts.update(module_constants(gw))
    routes: Dict[str, Any] = {}
    extra: Set[int] = set()
    for fn_name, authed in (('_handle', False), ('_route', True)):
        fn = find_function(gw.tree, fn_name)
        if fn is None:
            continue
        claimed: Set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            conds = _route_conditions(node.test)
            pattern = _route_pattern(conds)
            if pattern is None:
                continue
            method = conds.get('method', '*')
            info = _scan_route(pkg, gw, node.body, status_consts)
            handlers = info.pop('handlers')
            info = {'auth': authed, 'line': node.lineno, **info}
            routes[f'{method} {pattern}'] = info
            for sub in node.body:
                claimed.update(id(n) for n in ast.walk(sub))
            for h in handlers:
                claimed.update(id(n) for n in ast.walk(h))
        # statuses emitted in this function OUTSIDE any route branch:
        # the auth 401, the no-route 404/405 fallback
        for node in ast.walk(fn):
            if id(node) in claimed or not isinstance(node, ast.Call):
                continue
            if callable_name(node.func) in _STATUS_CALLS and node.args:
                extra.update(_resolve_statuses(node.args[0],
                                               status_consts))
    return routes, extra


def extract_transport(pkg: Package) -> Set[int]:
    """The transport layer's own status vocabulary (``ingress/http.py``):
    framing rejections, the handler-crash 500, the raw-bytes 503 shed
    (found by scanning bytes literals for the status line)."""
    mod = pkg.get(INGRESS_HTTP_PY)
    if mod is None:
        return set()
    consts = module_constants(mod)
    codes: Set[int] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) \
                and callable_name(node.func) in _STATUS_CALLS and node.args:
            codes.update(_resolve_statuses(node.args[0], consts))
        if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
            m = re.search(rb'HTTP/1\.1 (\d{3})', node.value)
            if m:
                codes.add(int(m.group(1)))
    return codes


def extract_metrics(pkg: Package) -> Dict[str, List[str]]:
    """``vft_ingress_*`` metric families registered by the gateway,
    with their label sets — per-endpoint cardinality is wire surface
    (dashboards and alerts key on these label names)."""
    gw = pkg.get(INGRESS_GATEWAY_PY)
    fams: Dict[str, List[str]] = {}
    if gw is None:
        return fams
    for node in ast.walk(gw.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ('counter', 'gauge', 'histogram')
                and node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith('vft_')):
            continue
        labels: List[str] = []
        for kw in node.keywords:
            if kw.arg == 'labels' and isinstance(kw.value, ast.Dict):
                labels = sorted(k.value for k in kw.value.keys
                                if isinstance(k, ast.Constant))
        fams[node.args[0].value] = labels
    return fams


# -- the assembled surface ----------------------------------------------------

def extract_surface(pkg: Package) -> Dict[str, Any]:
    proto = extract_protocol(pkg)
    server = extract_server_commands(pkg, proto)
    client = extract_client(pkg, proto)
    routes, extra_codes = extract_routes(pkg)
    commands: Dict[str, Any] = {}
    for cmd in sorted(set(server) | set(client)):
        sv = server.get(cmd, {})
        commands[cmd] = {
            'client_methods': client.get(cmd, {}).get('client_methods',
                                                      []),
            'request_fields': sv.get('request_fields', []),
            'response_fields': sv.get('response_fields', []),
            'error_fields': sv.get('error_fields', []),
        }
    lock_routes = {
        key: {k: v for k, v in info.items() if k != 'line'}
        for key, info in sorted(routes.items())
    }
    return {
        'schema': LOCK_SCHEMA,
        'version': proto.get('version'),
        'commands': commands,
        'routes': lock_routes,
        'transport': {
            'status': sorted(extract_transport(pkg) | extra_codes)},
        'metrics': extract_metrics(pkg),
        # extraction context the rules use (not written to the lock)
        '_proto': proto,
        '_server': server,
        '_client': client,
        '_route_lines': {k: v['line'] for k, v in routes.items()},
    }


def lock_view(surface: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in surface.items() if not k.startswith('_')}


# -- cross-layer sync rules ---------------------------------------------------

def check_sync(pkg: Package, surface: Dict[str, Any]) -> List[Finding]:
    """Client ↔ dispatch ↔ declared-COMMANDS agreement, both ways, and
    client submit fields the server's strict check would reject."""
    findings: List[Finding] = []
    proto, server, client = (surface['_proto'], surface['_server'],
                             surface['_client'])
    declared = set(proto.get('commands', ()))
    handled = set(server)
    spoken = set(client)
    line = proto.get('commands_line', 1)
    for cmd in sorted(declared - handled):
        findings.append(Finding(
            'wire-sync', SERVE_PROTOCOL_PY, line, f'undispatched:{cmd}',
            f'protocol.COMMANDS declares {cmd!r} but server _dispatch '
            f'has no handler branch for it'))
    for cmd in sorted(handled - declared):
        findings.append(Finding(
            'wire-sync', SERVE_SERVER_PY, server[cmd]['line'],
            f'undeclared:{cmd}',
            f'_dispatch handles {cmd!r} but protocol.COMMANDS does not '
            f'declare it — the documented vocabulary drifted'))
    for cmd in sorted(spoken - handled):
        findings.append(Finding(
            'wire-sync', SERVE_CLIENT_PY, client[cmd]['line'],
            f'client-only:{cmd}',
            f'ServeClient sends {cmd!r} but the server dispatch has no '
            f'handler — an old server answers "unknown cmd"'))
    for cmd in sorted(handled - spoken):
        findings.append(Finding(
            'wire-sync', SERVE_SERVER_PY, server[cmd]['line'],
            f'server-only:{cmd}',
            f'server handles {cmd!r} but no ServeClient method speaks '
            f'it — the reference client must cover the whole surface'))
    submit_ok = set(proto.get('submit_fields', ()))
    if submit_ok and 'submit' in client:
        for field in sorted(set(client['submit']['fields']) - submit_ok):
            findings.append(Finding(
                'wire-sync', SERVE_CLIENT_PY, client['submit']['line'],
                f'submit-field:{field}',
                f'ServeClient.submit sets field {field!r}, which is not '
                f'in protocol.SUBMIT_FIELDS — the server strict-rejects '
                f'the whole message'))
    return _filter(pkg, findings)


def check_error_echo(pkg: Package,
                     surface: Dict[str, Any]) -> List[Finding]:
    """Structured rejections must be correlatable: ``check_version``'s
    errors echo ``request_id``; tenant-scoped route errors carry
    ``tenant`` and ``request_id``."""
    findings: List[Finding] = []
    proto_mod = pkg.get(SERVE_PROTOCOL_PY)
    if proto_mod is not None:
        fn = find_function(proto_mod.tree, 'check_version')
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and callable_name(node.func) == 'error' \
                        and not any(kw.arg == 'request_id'
                                    for kw in node.keywords):
                    findings.append(Finding(
                        'error-echo', SERVE_PROTOCOL_PY, node.lineno,
                        'check_version:request_id',
                        'check_version rejection does not echo '
                        'request_id — a multiplexing client cannot '
                        'correlate the failure'))
    gw = pkg.get(INGRESS_GATEWAY_PY)
    if gw is not None:
        for key, info in surface['routes'].items():
            if not info.get('tenant_scoped'):
                continue
            for root in _route_handler_fns(pkg, gw, key):
                for node in ast.walk(root):
                    if not (isinstance(node, ast.Call)
                            and callable_name(node.func) == 'HttpError'):
                        continue
                    kwargs = {kw.arg for kw in node.keywords}
                    for need in ('tenant', 'request_id'):
                        if need not in kwargs:
                            findings.append(Finding(
                                'error-echo', INGRESS_GATEWAY_PY,
                                node.lineno,
                                f'route:{key}:{need}',
                                f'structured error on tenant-scoped '
                                f'route {key} does not carry '
                                f'{need!r} — cross-host correlation '
                                f'needs the echo'))
    return _filter(pkg, findings)


def _route_handler_fns(pkg: Package, gw: Module,
                       key: str) -> List[ast.AST]:
    """The handler functions a route branch calls (re-derived so the
    error-echo rule scans the same scope the extractor pinned)."""
    for fn_name in ('_handle', '_route'):
        fn = find_function(gw.tree, fn_name)
        if fn is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            conds = _route_conditions(node.test)
            pattern = _route_pattern(conds)
            if pattern is None:
                continue
            if f"{conds.get('method', '*')} {pattern}" != key:
                continue
            return list(node.body) + _self_call_closure(gw.tree,
                                                        node.body)
    return []


def check_docs(pkg: Package, surface: Dict[str, Any],
               docs_dir: Optional[Path]) -> List[Finding]:
    """Route/command tables in docs/ingress.md + docs/serving.md must
    match the extracted surface: an extracted route/command absent from
    the docs — or a documented route that no longer exists — is drift
    between what operators read and what the wire speaks."""
    findings: List[Finding] = []
    if docs_dir is None or not Path(docs_dir).is_dir():
        return findings

    def norm(p: str) -> str:
        return re.sub(r'<[^>]+>', '<*>', p.rstrip('/'))

    ingress_md = Path(docs_dir) / 'ingress.md'
    if ingress_md.exists():
        text = ingress_md.read_text()
        documented = {norm(m) for m in _DOC_ROUTE_RE.findall(text)}
        extracted = {norm(key.split(' ', 1)[1]): key
                     for key in surface['routes']}
        for path in sorted(set(extracted) - documented):
            key = extracted[path]
            findings.append(Finding(
                'doc-sync', INGRESS_GATEWAY_PY,
                surface['_route_lines'].get(key, 1), f'route:{key}',
                f'route {key} is not in the docs/ingress.md endpoint '
                f'table — document it in the same change'))
        for path in sorted(documented - set(extracted)):
            findings.append(Finding(
                'doc-sync', INGRESS_GATEWAY_PY, 1, f'stale-route:{path}',
                f'docs/ingress.md documents route {path!r}, which the '
                f'gateway no longer serves — stale docs'))
    serving_md = Path(docs_dir) / 'serving.md'
    if serving_md.exists():
        text = serving_md.read_text()
        for cmd in sorted(surface['commands']):
            if f'`{cmd}`' in text or f'"cmd":"{cmd}"' in text \
                    or f'"cmd": "{cmd}"' in text:
                continue
            findings.append(Finding(
                'doc-sync', SERVE_PROTOCOL_PY,
                surface['_proto'].get('commands_line', 1),
                f'command:{cmd}',
                f'loopback command {cmd!r} is not named in '
                f'docs/serving.md — document it in the same change'))
    return _filter(pkg, findings)


def _filter(pkg: Package, findings: List[Finding]) -> List[Finding]:
    return [f for f in findings
            if not _suppressed(pkg.get(f.file), f.rule, f.line)]


# -- the lock -----------------------------------------------------------------

def default_lock_path() -> Path:
    return Path(__file__).resolve().parent.parent.parent / DEFAULT_LOCK


def load_lock(path) -> Dict[str, Any]:
    path = Path(path)
    if not path.exists():
        return {}
    return json.loads(path.read_text() or '{}')


def write_lock(path, surface: Dict[str, Any],
               scopes: Iterable[str] = SCOPES) -> None:
    """Re-pin. A ``--scope`` subset replaces exactly the named sections
    and keeps the others from the existing lock; the full-scope default
    rebuilds the document, pruning stale sections."""
    scopes = tuple(scopes)
    doc = {} if set(scopes) == set(SCOPES) else load_lock(path)
    for scope in scopes:
        doc[scope] = surface[scope]
    doc['schema'] = LOCK_SCHEMA
    doc['version'] = surface['version']
    ordered = {k: doc[k] for k in ('schema', 'version', *SCOPES)
               if k in doc}
    Path(path).write_text(
        json.dumps(ordered, indent=1, sort_keys=True) + '\n')


def _parse_version(v: Any) -> Tuple[int, int]:
    try:
        major, minor = str(v).split('.', 1)
        return int(major), int(minor)
    except (TypeError, ValueError):
        return (0, 0)


def _advice(removal: bool, live_v: Any, lock_v: Any) -> str:
    lv, kv = _parse_version(live_v), _parse_version(lock_v)
    if removal:
        if lv[0] > kv[0]:
            return (f'breaking change already under the v{lv[0]} MAJOR '
                    f'bump — re-pin with --write-lock')
        return (f'BREAKING: requires a MAJOR bump of protocol.VERSION '
                f'({lock_v} -> {kv[0] + 1}.0) plus a --write-lock '
                f're-pin')
    if lv > kv:
        return 're-pin with --write-lock (MINOR bump already taken)'
    return (f'additive: requires a MINOR bump of protocol.VERSION '
            f'({lock_v} -> {kv[0]}.{kv[1] + 1}) plus a --write-lock '
            f're-pin')


def _diff_sets(findings: List[Finding], file: str, what: str,
               live: Iterable, locked: Iterable,
               live_v: Any, lock_v: Any) -> None:
    live_s, lock_s = set(live), set(locked)
    for item in sorted(lock_s - live_s, key=str):
        findings.append(Finding(
            'lock-drift', file, 0, f'{what}:-{item}',
            f'{what} {item!r} was removed from the wire — '
            f'{_advice(True, live_v, lock_v)}'))
    for item in sorted(live_s - lock_s, key=str):
        findings.append(Finding(
            'lock-drift', file, 0, f'{what}:+{item}',
            f'{what} {item!r} is new on the wire — '
            f'{_advice(False, live_v, lock_v)}'))


def diff_lock(surface: Dict[str, Any], lock: Dict[str, Any],
              scopes: Iterable[str] = SCOPES) -> List[Finding]:
    """Field-by-field drift between the live surface and the lock, with
    compatibility semantics: removals demand a MAJOR VERSION bump,
    additions a MINOR one; either way the lock re-pins via
    ``--write-lock`` so the diff is the review surface."""
    findings: List[Finding] = []
    if not lock:
        findings.append(Finding(
            'lock-drift', SERVE_PROTOCOL_PY, 0, 'lock:missing',
            f'no {DEFAULT_LOCK} — pin the wire surface with '
            f'--write-lock'))
        return findings
    live_v, lock_v = surface.get('version'), lock.get('version')
    if live_v != lock_v:
        findings.append(Finding(
            'lock-drift', SERVE_PROTOCOL_PY, 0,
            f'version:{lock_v}->{live_v}',
            f'protocol.VERSION is {live_v!r} but the lock pins '
            f'{lock_v!r} — re-pin with --write-lock'))
    scopes = set(scopes)
    if 'commands' in scopes:
        live_c, lock_c = surface['commands'], lock.get('commands', {})
        _diff_sets(findings, SERVE_PROTOCOL_PY, 'command',
                   live_c, lock_c, live_v, lock_v)
        for cmd in sorted(set(live_c) & set(lock_c)):
            for field in ('client_methods', 'request_fields',
                          'response_fields', 'error_fields'):
                _diff_sets(findings, SERVE_PROTOCOL_PY,
                           f'command {cmd} {field.replace("_", " ")}',
                           live_c[cmd].get(field, []),
                           lock_c[cmd].get(field, []), live_v, lock_v)
    if 'routes' in scopes:
        live_r, lock_r = surface['routes'], lock.get('routes', {})
        _diff_sets(findings, INGRESS_GATEWAY_PY, 'route',
                   live_r, lock_r, live_v, lock_v)
        for key in sorted(set(live_r) & set(lock_r)):
            for flag in ('auth', 'tenant_scoped'):
                if live_r[key].get(flag) != lock_r[key].get(flag):
                    findings.append(Finding(
                        'lock-drift', INGRESS_GATEWAY_PY, 0,
                        f'route {key}:{flag}',
                        f'route {key} changed {flag}: '
                        f'lock={lock_r[key].get(flag)} '
                        f'live={live_r[key].get(flag)} — '
                        f'{_advice(True, live_v, lock_v)}'))
            for field in ('status', 'request_fields', 'response_fields'):
                _diff_sets(findings, INGRESS_GATEWAY_PY,
                           f'route {key} {field.replace("_", " ")}',
                           live_r[key].get(field, []),
                           lock_r[key].get(field, []), live_v, lock_v)
            _diff_sets(findings, INGRESS_GATEWAY_PY,
                       f'route {key} error',
                       (tuple(e) for e in live_r[key].get('errors', [])),
                       (tuple(e) for e in lock_r[key].get('errors', [])),
                       live_v, lock_v)
    if 'transport' in scopes:
        _diff_sets(findings, INGRESS_HTTP_PY, 'transport status',
                   surface['transport']['status'],
                   lock.get('transport', {}).get('status', []),
                   live_v, lock_v)
    if 'metrics' in scopes:
        live_m = surface['metrics']
        lock_m = lock.get('metrics', {})
        _diff_sets(findings, INGRESS_GATEWAY_PY, 'metric family',
                   live_m, lock_m, live_v, lock_v)
        for fam in sorted(set(live_m) & set(lock_m)):
            _diff_sets(findings, INGRESS_GATEWAY_PY,
                       f'metric {fam} label',
                       live_m[fam], lock_m[fam], live_v, lock_v)
    return findings


# -- CLI ----------------------------------------------------------------------

def run(pkg: Package, docs_dir: Optional[Path]) -> Tuple[Dict[str, Any],
                                                         List[Finding]]:
    surface = extract_surface(pkg)
    findings = (check_sync(pkg, surface)
                + check_error_echo(pkg, surface)
                + check_docs(pkg, surface, docs_dir))
    return surface, findings


def main(argv=None, jax_preloaded=None) -> int:
    parser = argparse.ArgumentParser(
        prog='vft-wire',
        description='static wire-contract checker over the loopback + '
                    'ingress API surface (docs/static_analysis.md)')
    parser.add_argument('--root', help='package root to analyze '
                        '(default: the installed video_features_tpu/)')
    parser.add_argument('--package-name', default='video_features_tpu')
    parser.add_argument('--docs-dir', help='docs directory for the '
                        'doc-sync rule (default: <repo>/docs; doc-sync '
                        'is skipped when absent)')
    parser.add_argument('--lock', help='lock file path (default: '
                        f'<repo>/{DEFAULT_LOCK})')
    parser.add_argument('--write-lock', action='store_true',
                        help='re-pin: write the live surface for the '
                        'selected --scope sections and exit 0')
    parser.add_argument('--scope', default=','.join(SCOPES),
                        help='comma-separated lock sections to check / '
                        f're-pin (default: all — {",".join(SCOPES)}); '
                        'a subset --write-lock merges, the full scope '
                        'prunes')
    parser.add_argument('--list-rules', action='store_true')
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES:
            print(rule)
        return EXIT_CLEAN

    jax_was_loaded = ('jax' in sys.modules if jax_preloaded is None
                      else jax_preloaded)

    pkg_root = Path(__file__).resolve().parent.parent
    repo_root = pkg_root.parent
    docs_dir: Optional[Path] = repo_root / 'docs'
    if args.root:
        pkg_root = Path(args.root)
        docs_dir = None
    if args.docs_dir:
        docs_dir = Path(args.docs_dir)
    lock_path = Path(args.lock) if args.lock else default_lock_path()
    scopes = tuple(s.strip() for s in args.scope.split(',') if s.strip())
    unknown = set(scopes) - set(SCOPES)
    if unknown:
        print(f'vft-wire: unknown scope(s) {sorted(unknown)}; known: '
              f'{", ".join(SCOPES)}', file=sys.stderr)
        return EXIT_ERROR

    try:
        pkg = Package(pkg_root, args.package_name)
        surface, findings = run(pkg, docs_dir)
    except SyntaxError as e:
        print(f'vft-wire: parse error: {e}', file=sys.stderr)
        return EXIT_ERROR

    if args.write_lock:
        write_lock(lock_path, lock_view(surface), scopes)
        n_cmds = len(surface['commands'])
        n_routes = len(surface['routes'])
        print(f'vft-wire: pinned {n_cmds} command(s), {n_routes} '
              f'route(s) at wire v{surface["version"]} to {lock_path}')
        for f in findings:
            print(f'(unresolved) {f.render(pkg_root)}', file=sys.stderr)
        return EXIT_CLEAN

    lock = load_lock(lock_path)
    findings.extend(diff_lock(surface, lock, scopes))
    for f in findings:
        print(f.render(pkg_root))
    print(f'vft-wire: {len(findings)} finding(s) across '
          f'{len(surface["commands"])} commands, '
          f'{len(surface["routes"])} routes (wire v{surface["version"]} '
          f'vs lock v{lock.get("version")})',
          file=sys.stderr)
    # the same purity self-enforcement as vft-lint: everything above is
    # ast over source text — jax appearing mid-run is a checker bug
    if 'jax' in sys.modules and not jax_was_loaded:
        print('vft-wire: FATAL: the analyzer process imported jax',
              file=sys.stderr)
        return EXIT_IMPURE
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == '__main__':
    sys.exit(main())
