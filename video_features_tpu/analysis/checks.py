"""vft-lint rules: the codebase's own contracts, as checkers.

Every rule here enforces an invariant that is *stated* somewhere in this
repo — a docstring, a CHANGES.md hardening note, a review fix — but was
previously enforced nowhere mechanically. Each checker returns
:class:`~video_features_tpu.analysis.core.Finding` objects with a stable
rule id; suppression is per-line (``# vft-lint: ok=<rule>``) with the
rationale next to the code it excuses (see ``docs/static_analysis.md``
for the rule catalog).

Rule ids (stable — baselines and suppressions key on them):

  spawn-purity            farm worker closure must not import jax/flax
  recipe-picklable        recipes are picklable by construction
  knob-classification     every injected knob is classified + validated
  knob-registry           exclusion sets derive from the one registry
  swallowed-exception     broad excepts re-raise or report via obs.events
  stdout-purity           stdout belongs to the feature stream
  contract-key-sync       export schemas match their pinned contracts
  stage-vocabulary        stage names come from the canonical STAGES
  thread-discipline       module-level mutables declare their lock
  lock-order              acyclic lock graph; no untimed blocking call
                          while a lock is held
  wire-literal            status codes / command names come from the
                          shared constants the wire lock anchors on
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from video_features_tpu.analysis.core import (
    CACHE_KEY_PY, CONFIG_PY, FARM_RECIPES_PY, FARM_WORKER_PY,
    HOST_TRANSFORMS_PY, INGRESS_HTTP_PY, OBS_MANIFEST_PY, SERVE_CLIENT_PY,
    SERVE_METRICS_PY, SERVE_PROTOCOL_PY, SERVE_SERVER_PY,
    TRACING_PY, Finding, Module, Package, assigned_dict_keys,
    callable_name, dict_literal_str_keys, find_assignment, find_function,
    module_constants, module_level_statements, set_literal_values,
    str_constants_in,
)
from video_features_tpu.analysis.imports import (
    chain, module_imports, spawn_closure,
)

# -- spawn-purity ------------------------------------------------------------

SPAWN_ROOTS = (FARM_WORKER_PY, FARM_RECIPES_PY, HOST_TRANSFORMS_PY)
FORBIDDEN_SPAWN_IMPORTS = ('jax', 'flax')


def closure_forbidden_imports(package: Package, roots: Iterable[str],
                              rule: str, contract: str) -> List[Finding]:
    """Module-level jax/flax imports anywhere in the static import
    closure of ``roots`` — shared by the spawn-purity rule and the
    analyzer's own import-chain self-check."""
    findings: List[Finding] = []
    closure = spawn_closure(package, roots)
    for rel in sorted(closure):
        mod = package.get(rel)
        if mod is None:
            continue
        for edge in module_imports(mod, package):
            if edge.level != 'module':
                continue           # gated lazy imports are the idiom
            root = edge.target.split('.')[0]
            if root in FORBIDDEN_SPAWN_IMPORTS:
                via = ' -> '.join(chain(closure, rel))
                findings.append(Finding(
                    rule, rel, edge.line, f'import:{edge.target}',
                    f'module-level import of {edge.target!r} inside the '
                    f'{contract} closure ({via})'))
    return findings


def check_spawn_purity(package: Package) -> List[Finding]:
    """The decode-farm worker contract (PR 6): ``farm/worker.py``,
    ``farm/recipes.py``, and ``ops/host_transforms.py`` run in spawned
    processes whose import footprint must stay at numpy/cv2 — their
    transitive static import closure (function-level intra-package
    imports included: a recipe's lazy helper import runs in the worker
    at decode time) must never reach a module-level jax/flax import."""
    return closure_forbidden_imports(
        package, SPAWN_ROOTS, 'spawn-purity',
        'spawn-worker (decode workers must stay jax-free — '
        'farm/worker.py contract)')


# -- recipe-picklable --------------------------------------------------------

# the shared spelling lives in analysis/core.py (vft-wire resolves call
# targets the same way)
_callable_name = callable_name


def check_recipe_picklable(package: Package) -> List[Finding]:
    """Recipes cross the spawn boundary by pickle (PR 6): their FIELDS
    must be plain data. Two enforcement points: (a) ``__init__`` of any
    ``*Recipe`` class in farm/recipes.py must not create lambdas /
    nested defs / local classes (anything it binds would land in a
    field), and (b) no call site anywhere may pass a lambda into a
    ``*Recipe(...)`` constructor — transforms travel as named SPECS
    (``ops.host_transforms``), never as callables."""
    findings: List[Finding] = []
    recipes = package.get(FARM_RECIPES_PY)
    if recipes is not None:
        for node in ast.walk(recipes.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name.endswith('Recipe')):
                continue
            init = find_function(node, '__init__')
            if init is None:
                continue
            for sub in ast.walk(init):
                if isinstance(sub, (ast.Lambda, ast.ClassDef)) or \
                        (isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                         and sub is not init):
                    findings.append(Finding(
                        'recipe-picklable', FARM_RECIPES_PY, sub.lineno,
                        f'init:{node.name}',
                        f'{node.name}.__init__ creates a '
                        f'{type(sub).__name__}: recipe fields must be '
                        f'plain picklable data (spawn contract)'))
    for rel, mod in package.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _callable_name(node.func).endswith('Recipe'):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Lambda):
                        findings.append(Finding(
                            'recipe-picklable', rel, sub.lineno,
                            f'call:{_callable_name(node.func)}',
                            f'lambda passed into '
                            f'{_callable_name(node.func)}(...): recipe '
                            f'fields cross the spawn boundary by pickle '
                            f'— use a named transform spec'))
    return findings


# -- knob-classification -----------------------------------------------------

KNOB_CLASS_VALUES = ('neither', 'pool_only', 'fingerprint_only', 'both')
_DEFAULTS_RE = re.compile(r'^[A-Z][A-Z_]*_DEFAULTS$')
# server-level namespaces: validated wholesale by split_serve_config's /
# split_fleet_config's unknown-key rejection and never merged into
# per-request configs, so fingerprint/pool-key classification does not
# apply
_EXEMPT_DEFAULTS = ('SERVE_DEFAULTS', 'FLEET_DEFAULTS')


def _defaults_dicts(mod: Module) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in module_level_statements(mod.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and _DEFAULTS_RE.match(t.id):
                    out[t.id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            t = node.target
            if isinstance(t, ast.Name) and _DEFAULTS_RE.match(t.id):
                out[t.id] = node.value
    return out


def check_knob_classification(package: Package) -> List[Finding]:
    """Every knob the config system injects (``*_DEFAULTS`` in
    config.py, SERVE_DEFAULTS exempt) must be (a) classified in the one
    declarative ``KNOB_CLASSIFICATION`` registry — the single source of
    truth the cache fingerprint and the serve pool key derive their
    exclusion sets from — and (b) named in ``sanity_check`` (an
    unvalidated knob is the drift PRs 5-8 each re-fixed by hand)."""
    findings: List[Finding] = []
    cfg = package.get(CONFIG_PY)
    if cfg is None:
        return findings
    reg_node = find_assignment(cfg.tree, 'KNOB_CLASSIFICATION')
    if reg_node is None:
        findings.append(Finding(
            'knob-classification', CONFIG_PY, 1, 'registry:missing',
            'config.py must declare the KNOB_CLASSIFICATION registry '
            '(knob -> neither|pool_only|fingerprint_only|both)'))
        return findings
    registry: Dict[str, str] = {}
    if isinstance(reg_node, ast.Dict):
        for k, v in zip(reg_node.keys, reg_node.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                    and isinstance(v, ast.Constant):
                registry[k.value] = v.value
                if v.value not in KNOB_CLASS_VALUES:
                    findings.append(Finding(
                        'knob-classification', CONFIG_PY, v.lineno,
                        f'class:{k.value}',
                        f'knob {k.value!r} classified as {v.value!r}; '
                        f'must be one of {KNOB_CLASS_VALUES}'))
    sanity = find_function(cfg.tree, 'sanity_check')
    sanity_literals = str_constants_in(sanity) if sanity else set()
    for dict_name, node in _defaults_dicts(cfg).items():
        if dict_name in _EXEMPT_DEFAULTS:
            continue
        for key in dict_literal_str_keys(node):
            if key not in registry:
                findings.append(Finding(
                    'knob-classification', CONFIG_PY, node.lineno,
                    f'unclassified:{key}',
                    f'knob {key!r} ({dict_name}) is missing from '
                    f'KNOB_CLASSIFICATION: say whether it belongs in the '
                    f'cache fingerprint and the serve pool key'))
            if key not in sanity_literals:
                findings.append(Finding(
                    'knob-classification', CONFIG_PY, node.lineno,
                    f'unvalidated:{key}',
                    f'knob {key!r} ({dict_name}) is never named in '
                    f'sanity_check: every injected knob must be '
                    f'validated (ValueError, not assert)'))
    return findings


# -- knob-registry (single source of truth) ----------------------------------

_EXCLUDE_NAME_RE = re.compile(r'EXCLUDE')
_KNOB_CONSUMERS = (CACHE_KEY_PY, SERVE_SERVER_PY)


def check_knob_registry_single_source(package: Package) -> List[Finding]:
    """The fingerprint/pool-key exclusion sets must DERIVE from
    ``config.KNOB_CLASSIFICATION`` (``knob_exclude``), never be
    hand-maintained literals in the consumers — three hand-synced copies
    of this list drifted in four consecutive PRs."""
    findings: List[Finding] = []
    for rel in _KNOB_CONSUMERS:
        mod = package.get(rel)
        if mod is None:
            continue
        uses_registry = False
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and \
                    any(a.name == 'knob_exclude' for a in node.names):
                uses_registry = True
            if isinstance(node, ast.Call) and \
                    _callable_name(node.func) == 'knob_exclude':
                uses_registry = True
        for node in module_level_statements(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (isinstance(t, ast.Name)
                        and _EXCLUDE_NAME_RE.search(t.id)):
                    continue
                if len(set_literal_values(node.value)) >= 3:
                    findings.append(Finding(
                        'knob-registry', rel, node.lineno,
                        f'literal:{t.id}',
                        f'{t.id} is a locally-defined exclusion list; '
                        f'derive it from config.KNOB_CLASSIFICATION via '
                        f'knob_exclude() so the classification has one '
                        f'source of truth'))
        if not uses_registry:
            findings.append(Finding(
                'knob-registry', rel, 1, 'registry:unused',
                f'{rel} must derive its key-exclusion set from '
                f'config.knob_exclude()'))
    return findings


# -- swallowed-exception -----------------------------------------------------

# a handler that calls any of these (or raises) has surfaced the error;
# names cover obs.events (event, log_*), warnings.warn, and logger methods
_REPORT_CALL_NAMES = ('event', 'warn', 'warning', 'error', 'exception',
                      'critical')


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ('Exception', 'BaseException') for n in names)


def _handler_reports(handler: ast.ExceptHandler,
                     reporting_helpers: Set[str]) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _callable_name(node.func)
            if name in _REPORT_CALL_NAMES or name.startswith('log_') \
                    or name in reporting_helpers:
                return True
    return False


def _reporting_helpers(mod: Module) -> Set[str]:
    """Same-module functions whose body directly calls a report function
    (one hop of indirection: ``doom_batch`` → ``log_batch_error``)."""
    helpers: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _callable_name(sub.func)
                if name in _REPORT_CALL_NAMES or name.startswith('log_'):
                    helpers.add(node.name)
                    break
    return helpers


def check_swallowed_exceptions(package: Package) -> List[Finding]:
    """The reference repo's defining bug as a rule: a bare ``except:``
    or ``except Exception`` whose body neither re-raises nor reports
    through ``obs.events`` (or ``warnings.warn`` / a logger) is exactly
    the handler that *looks* handled while silently eating a KeyError
    for seven of eight extractors. Deliberate best-effort teardown sites
    carry an inline suppression with their rationale."""
    findings: List[Finding] = []
    for rel, mod in package.modules.items():
        helpers = _reporting_helpers(mod)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.ExceptHandler)
                    and _is_broad_handler(node)
                    and not _handler_reports(node, helpers)):
                continue
            # the rationale comment conventionally LEADS the handler
            # body — accept a marker anywhere in the header region
            # (except-line through the first body statement)
            body_first = node.body[0].lineno if node.body else node.lineno
            if not mod.suppressed_in('swallowed-exception',
                                     node.lineno, body_first):
                findings.append(Finding(
                    'swallowed-exception', rel, node.lineno,
                    f'except:{mod.scope_of(node)}',
                    'broad except neither re-raises nor reports via '
                    'obs.events / warnings.warn — the silent-KeyError '
                    'failure mode (route it through obs.events, or '
                    'suppress with a rationale if it is best-effort '
                    'teardown)'))
    return findings


# -- stdout-purity -----------------------------------------------------------

# CLI entry points own their stdout
_STDOUT_WHITELIST = ('cli.py', '__main__.py')


def _inside_print_mode_branch(node: ast.AST,
                              parents: Dict[ast.AST, ast.AST]) -> bool:
    """True when the call sits in the BODY (not the else) of an
    ``if <...on_extraction...> == 'print'`` test — the one whitelisted
    feature-stream path."""
    cur: Optional[ast.AST] = node
    while cur is not None:
        prev, cur = cur, parents.get(cur)
        if isinstance(cur, ast.If):
            test = cur.test
            names = {n.attr for n in ast.walk(test)
                     if isinstance(n, ast.Attribute)}
            names |= {n.id for n in ast.walk(test)
                      if isinstance(n, ast.Name)}
            if 'on_extraction' in names \
                    and 'print' in str_constants_in(test) \
                    and any(prev is s or prev in ast.walk(s)
                            for s in cur.body):
                return True
    return False


def check_stdout_purity(package: Package) -> List[Finding]:
    """stdout belongs to the feature stream (``on_extraction=print``):
    a bare ``print(...)`` anywhere else interleaves with it and breaks
    downstream parsers — the reason PR 2 moved the packing fallback to
    ``warnings.warn`` and PR 4 moved error prints to ``obs.events``.
    Allowed: CLI entry modules, ``print(..., file=...)`` (an explicit
    stream is a decision), and the on_extraction=print branch itself."""
    findings: List[Finding] = []
    for rel, mod in package.modules.items():
        if rel in _STDOUT_WHITELIST:
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == 'print'):
                continue
            if any(kw.arg == 'file' for kw in node.keywords):
                continue
            if _inside_print_mode_branch(node, mod.parents):
                continue
            findings.append(Finding(
                'stdout-purity', rel, node.lineno,
                f'print:{mod.scope_of(node)}',
                'bare print() writes to stdout, which the feature stream '
                'owns — use warnings.warn / obs.events, pass file=, or '
                'suppress with a rationale for a deliberate stdout '
                'surface'))
    return findings


# -- contract-key-sync -------------------------------------------------------

_CONTRACTS_TEST_FILE = 'test_obs.py'


def _pinned_set(tests_tree: Optional[ast.Module],
                name: str) -> Optional[Set[str]]:
    if tests_tree is None:
        return None
    node = find_assignment(tests_tree, name)
    if node is None:
        return None
    vals = set_literal_values(node)
    return vals or None


def _compare(rule: str, rel: str, line: int, what: str,
             built: Set[str], pinned: Set[str]) -> List[Finding]:
    findings = []
    for key in sorted(built - pinned):
        findings.append(Finding(
            rule, rel, line, f'{what}:unpinned:{key}',
            f'{what} constructs key {key!r} that the pinned contract '
            f'set (tests/{_CONTRACTS_TEST_FILE}) does not name — update '
            f'the contract in the same change'))
    for key in sorted(pinned - built):
        findings.append(Finding(
            rule, rel, line, f'{what}:stale:{key}',
            f'pinned contract key {key!r} is never constructed by '
            f'{what} — stale contract entry (or a key went missing)'))
    return findings


def check_contract_keys(package: Package) -> List[Finding]:
    """The export schemas scrapers depend on — serve metrics document,
    run manifest, tracer stage records — must match the contract sets
    pinned in tests/test_obs.py *exactly*, in both directions: a key
    constructed but unpinned drifts silently; a key pinned but never
    constructed is a stale contract."""
    findings: List[Finding] = []
    tests = package.parse_tests_file(_CONTRACTS_TEST_FILE)

    metrics = package.get(SERVE_METRICS_PY)
    pinned = _pinned_set(tests, 'METRICS_DOC_KEYS')
    if metrics is not None and pinned is not None:
        built: Set[str] = set()
        fn = find_function(metrics.tree, 'build_metrics')
        if fn is not None:
            built |= assigned_dict_keys(fn, 'doc')
        fn = find_function(metrics.tree, 'snapshot')
        if fn is not None:
            built |= assigned_dict_keys(fn, 'out')
        findings += _compare('contract-key-sync', SERVE_METRICS_PY, 1,
                             'serve metrics document', built, pinned)

    manifest = package.get(OBS_MANIFEST_PY)
    pinned = _pinned_set(tests, 'MANIFEST_KEYS')
    if manifest is not None and pinned is not None:
        fn = find_function(manifest.tree, 'document')
        built = set()
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Dict):
                    built |= set(dict_literal_str_keys(node.value))
        findings += _compare('contract-key-sync', OBS_MANIFEST_PY,
                             fn.lineno if fn else 1,
                             'run manifest document', built, pinned)

    tracing = package.get(TRACING_PY)
    pinned = _pinned_set(tests, 'TRACER_RECORD_KEYS')
    if tracing is not None and pinned is not None:
        fn = find_function(tracing.tree, '_stat_record')
        built = assigned_dict_keys(fn, 'rec') if fn is not None else set()
        findings += _compare('contract-key-sync', TRACING_PY,
                             fn.lineno if fn else 1,
                             'tracer stage record', built, pinned)
    return findings


# -- stage-vocabulary --------------------------------------------------------

_STAGE_METHODS = ('stage', 'wrap_iter', 'add_occupancy')


def _stage_literal(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def check_stage_vocabulary(package: Package) -> List[Finding]:
    """Stage names are a shared vocabulary (``utils.tracing.STAGES``):
    dashboards key ``vft_stage_*`` families and bench ``stage_reports``
    on them. Two checks: the tuple must equal the CANONICAL_STAGES
    contract pinned in tests/test_obs.py, and every literal stage name
    recorded anywhere in the package must come from it."""
    findings: List[Finding] = []
    tracing = package.get(TRACING_PY)
    if tracing is None:
        return findings
    stages_node = find_assignment(tracing.tree, 'STAGES')
    stages = set_literal_values(stages_node) if stages_node else set()
    if not stages:
        findings.append(Finding(
            'stage-vocabulary', TRACING_PY, 1, 'stages:missing',
            'utils/tracing.py must declare the canonical STAGES tuple'))
        return findings
    pinned = _pinned_set(package.parse_tests_file(_CONTRACTS_TEST_FILE),
                         'CANONICAL_STAGES')
    if pinned is not None and pinned != stages:
        drift = sorted(stages ^ pinned)
        findings.append(Finding(
            'stage-vocabulary', TRACING_PY,
            stages_node.lineno, 'stages:contract',
            f'STAGES and the CANONICAL_STAGES contract '
            f'(tests/{_CONTRACTS_TEST_FILE}) disagree on {drift} — '
            f'renaming a stage is an intentional, test-visible event'))
    for rel, mod in package.modules.items():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            name = None
            if attr in _STAGE_METHODS:
                name = _stage_literal(node)
            elif attr == 'add' and 'tracer' in ast.unparse(node.func.value):
                name = _stage_literal(node)
            if name is not None and name not in stages:
                findings.append(Finding(
                    'stage-vocabulary', rel, node.lineno, f'stage:{name}',
                    f'stage name {name!r} is not in the canonical STAGES '
                    f'vocabulary (utils/tracing.py) — add it there (and '
                    f'to the pinned contract) or reuse an existing name'))
    return findings


# -- thread-discipline -------------------------------------------------------

_CONCURRENT_DIRS = ('serve/', 'farm/', 'ingress/')
_MUTABLE_CALLS = ('dict', 'list', 'set', 'OrderedDict', 'defaultdict',
                  'deque')
_LOCK_VALUES = ('immutable',)


def _is_mutable_container(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) \
            and _callable_name(node.func) in _MUTABLE_CALLS:
        return True
    return False


def check_thread_discipline(package: Package) -> List[Finding]:
    """Modules under serve/, farm/, ingress/ run threaded by design.
    A module-level mutable container is shared state: it must be named
    in the module's ``_LOCKED_BY`` declaration, mapping it to the
    module-level lock that guards it — or to ``'immutable'`` when it is
    a constant that is never written after import."""
    findings: List[Finding] = []
    for rel, mod in package.modules.items():
        if not rel.startswith(_CONCURRENT_DIRS):
            continue
        locked_node = find_assignment(mod.tree, '_LOCKED_BY')
        locked: Dict[str, str] = {}
        if isinstance(locked_node, ast.Dict):
            for k, v in zip(locked_node.keys, locked_node.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                    locked[k.value] = v.value
        module_names = set()
        for stmt in module_level_statements(mod.tree):
            if isinstance(stmt, ast.Assign):
                module_names.update(t.id for t in stmt.targets
                                    if isinstance(t, ast.Name))
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                module_names.add(stmt.target.id)
        for stmt in module_level_statements(mod.tree):
            targets: List[ast.Name] = []
            value: Optional[ast.AST] = None
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                targets = [stmt.target]
                value = stmt.value
            if value is None or not _is_mutable_container(value):
                continue
            for t in targets:
                name = t.id
                if name.startswith('__') or name == '_LOCKED_BY':
                    continue
                if name not in locked:
                    findings.append(Finding(
                        'thread-discipline', rel, stmt.lineno,
                        f'unlocked:{name}',
                        f'module-level mutable {name!r} in a threaded '
                        f'subsystem has no _LOCKED_BY entry — name the '
                        f"lock that guards it (or 'immutable' for a "
                        f'write-once constant)'))
                elif locked[name] not in _LOCK_VALUES \
                        and locked[name] not in module_names:
                    findings.append(Finding(
                        'thread-discipline', rel, stmt.lineno,
                        f'missing-lock:{name}',
                        f'_LOCKED_BY maps {name!r} to '
                        f'{locked[name]!r}, which is not a module-level '
                        f'name in {rel}'))
    return findings


# -- lock-order --------------------------------------------------------------

# receiver-less blocking methods: a zero-positional-arg call to one of
# these blocks until someone else makes progress. The zero-arg shape is
# the discriminator that keeps dict.get(key) / str.join(seq) /
# os.path.join(a, b) out of scope — Queue.get(), Connection.recv() and
# Thread/Process.join() are exactly the forms with no positional args.
_BLOCKING_METHODS = ('get', 'recv', 'join')
_LOCK_FACTORY_NAMES = ('Lock', 'RLock', 'Condition', 'Semaphore',
                       'BoundedSemaphore')


# 'lock'/'rlock' as the final identifier TOKEN ('_lock', 'build_lock',
# '_LIVE_LOCK', 'self._lock') — token-anchored so 'block' / 'clock' /
# '_nonblocking_guard' context managers are never mistaken for locks
_LOCK_NAME_RE = re.compile(r'(?:^|_)r?lock$')


def _lock_exprs(node: ast.With, module_locks: Set[str]) -> List[str]:
    """Unparsed context expressions of a ``with`` that are lock
    acquisitions: any KNOWN module-level lock name (``_LOCKED_BY``
    values / ``threading.Lock()`` assignments — whatever it is called),
    plus any name whose final dotted segment is a 'lock'-ending token
    (the ``self._lock`` instance idiom). ``.acquire()``-style usage is
    not the codebase idiom."""
    out = []
    for item in node.items:
        src = ast.unparse(item.context_expr)
        if src in module_locks:
            out.append(src)
        elif '(' not in src and \
                _LOCK_NAME_RE.search(src.rsplit('.', 1)[-1].lower()):
            out.append(src)
    return out


def _module_level_locks(mod: Module) -> Set[str]:
    """Module-level lock names: ``_LOCKED_BY`` values (≠ 'immutable')
    plus any module-level ``threading.Lock()``-family assignment."""
    locks: Set[str] = set()
    locked_node = find_assignment(mod.tree, '_LOCKED_BY')
    if isinstance(locked_node, ast.Dict):
        for v in locked_node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str) \
                    and v.value not in _LOCK_VALUES:
                locks.add(v.value)
    for stmt in module_level_statements(mod.tree):
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if _callable_name(stmt.value.func) in _LOCK_FACTORY_NAMES:
                locks.update(t.id for t in stmt.targets
                             if isinstance(t, ast.Name))
    return locks


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    """The blocking method name when ``node`` is a no-timeout blocking
    call, else None. ``q.get(timeout=t)`` / ``t.join(deadline)`` /
    ``q.get(False)`` (any positional arg) pass."""
    if not isinstance(node.func, ast.Attribute):
        return None
    if node.func.attr not in _BLOCKING_METHODS or node.args:
        return None
    if any(kw.arg in ('timeout', 'block') for kw in node.keywords):
        return None
    return node.func.attr


def check_lock_order(package: Package) -> List[Finding]:
    """Deadlock statics for the threaded subsystems (serve/, farm/,
    ingress/). Two checks over the lock-acquisition structure:

      * **blocking-under-lock** — a ``Queue.get()`` /
        ``Connection.recv()`` / ``join()`` with no timeout while ANY
        lock is held (module-level locks from ``_LOCKED_BY`` /
        ``threading.Lock()`` assignments, or a ``with self._lock:``
        style instance lock) waits on another thread's progress while
        holding what that thread may need — the textbook shape of the
        stalls PR 6/8 hardening notes fixed by hand;
      * **cycle** — the static acquisition graph (edges: lock A held
        when lock B is acquired, per ``with`` nesting; lock identity is
        (module, expression) — a syntactic approximation, see
        docs/static_analysis.md) must be acyclic: an A→B edge in one
        function and B→A in another is lock-order inversion.

    Nested ``def``/``lambda`` bodies reset the held-set (they execute
    later, not under the ``with``)."""
    findings: List[Finding] = []
    edges: Dict[tuple, Set[tuple]] = {}
    edge_sites: Dict[tuple, tuple] = {}

    for rel, mod in package.modules.items():
        if not rel.startswith(_CONCURRENT_DIRS):
            continue
        module_locks = _module_level_locks(mod)

        def lock_id(expr: str, rel=rel, module_locks=module_locks) -> tuple:
            # module-level locks get a module-scoped identity; instance
            # locks (self._lock) one per (module, expression)
            return (rel, expr if expr in module_locks else f'<{expr}>')

        class _Walker(ast.NodeVisitor):
            def __init__(self, mod=mod, rel=rel):
                self.mod, self.rel = mod, rel
                self.held: List[str] = []

            def visit_With(self, node: ast.With) -> None:
                locks = _lock_exprs(node, module_locks)
                for lk in locks:
                    for held in self.held:
                        a, b = lock_id(held), lock_id(lk)
                        if a != b:
                            edges.setdefault(a, set()).add(b)
                            edge_sites.setdefault((a, b),
                                                  (self.rel, node.lineno))
                self.held.extend(locks)
                self.generic_visit(node)
                if locks:
                    del self.held[-len(locks):]

            visit_AsyncWith = visit_With

            def visit_Call(self, node: ast.Call) -> None:
                name = _is_blocking_call(node)
                if name and self.held \
                        and not self.mod.suppressed('lock-order',
                                                    node.lineno):
                    findings.append(Finding(
                        'lock-order', self.rel, node.lineno,
                        f'blocking:{self.mod.scope_of(node)}.{name}',
                        f'{ast.unparse(node.func)}() blocks with no '
                        f'timeout while holding '
                        f'{" + ".join(self.held)} — the holder waits on '
                        f'another thread that may need the lock (add a '
                        f'timeout, or move the wait outside the lock)'))
                self.generic_visit(node)

            def _reset_scope(self, node) -> None:
                held, self.held = self.held, []
                self.generic_visit(node)
                self.held = held

            def visit_FunctionDef(self, node) -> None:
                self._reset_scope(node)

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

        _Walker().visit(mod.tree)

    # cycle sweep over the global acquisition graph
    def _find_cycle(start: tuple) -> Optional[List[tuple]]:
        path: List[tuple] = []
        on_path: Set[tuple] = set()
        done: Set[tuple] = set()

        def dfs(node: tuple) -> Optional[List[tuple]]:
            if node in on_path:
                return path[path.index(node):] + [node]
            if node in done:
                return None
            path.append(node)
            on_path.add(node)
            for nxt in sorted(edges.get(node, ())):
                cyc = dfs(nxt)
                if cyc is not None:
                    return cyc
            path.pop()
            on_path.discard(node)
            done.add(node)
            return None

        return dfs(start)

    reported: Set[frozenset] = set()
    for start in sorted(edges):
        cyc = _find_cycle(start)
        if cyc is None:
            continue
        ident = frozenset(cyc)
        if ident in reported:
            continue
        reported.add(ident)
        rel, line = edge_sites.get((cyc[0], cyc[1]), (cyc[0][0], 1))
        chain_txt = ' -> '.join(f'{r}:{n}' for r, n in cyc)
        findings.append(Finding(
            'lock-order', rel, line,
            f'cycle:{"|".join(sorted(n for _, n in set(cyc)))}',
            f'lock-acquisition cycle: {chain_txt} — two call paths '
            f'taking these locks in opposite orders can deadlock'))
    return findings


# -- wire-literal ------------------------------------------------------------

# call positions whose first positional argument IS an HTTP status code
_WIRE_STATUS_CALLS = ('HttpError', 'send_json', 'send', 'start_chunked')


def check_wire_literal(package: Package) -> List[Finding]:
    """The wire surface is pinned statically (``WIRE.lock.json``,
    analysis/wire.py), which only works if the surface is SPELLED in one
    place: status codes come from ``ingress/http.py``'s named constants
    and command names from ``serve/protocol.py``'s ``CMD_*`` constants.
    An inline ``404`` in a status position or an inline ``'submit'`` in
    a cmd position is invisible to the extractor — the same collapse
    the knob-registry rule already did for exclusion lists."""
    findings: List[Finding] = []
    # (a) inline ints in status positions anywhere under serve/ingress
    # (ingress/http.py itself DEFINES the vocabulary and is exempt)
    for rel, mod in package.modules.items():
        if not rel.startswith(('serve/', 'ingress/')) \
                or rel == INGRESS_HTTP_PY:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callable_name(node.func) not in _WIRE_STATUS_CALLS \
                    or not node.args:
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, int) \
                    and not mod.suppressed('wire-literal', node.lineno):
                findings.append(Finding(
                    'wire-literal', rel, node.lineno,
                    f'status:{a0.value}',
                    f'inline status code {a0.value} in a '
                    f'{_callable_name(node.func)}(...) call — use the '
                    f'named constant from ingress/http.py so vft-wire '
                    f'can pin the route status-code set statically'))
    # (b) inline command strings in cmd positions in the loopback
    # server/client (serve/protocol.py defines CMD_* and is exempt)
    commands = set(module_constants(package.get(SERVE_PROTOCOL_PY),
                                    types=(str,),
                                    prefix='CMD_').values())
    if not commands:
        return findings
    for rel in (SERVE_SERVER_PY, SERVE_CLIENT_PY):
        mod = package.get(rel)
        if mod is None:
            continue
        for node in ast.walk(mod.tree):
            bad: Optional[ast.Constant] = None
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                names = {s.id for s in sides if isinstance(s, ast.Name)}
                names |= {s.attr for s in sides
                          if isinstance(s, ast.Attribute)}
                if 'cmd' in names:
                    for s in sides:
                        if isinstance(s, ast.Constant) \
                                and s.value in commands:
                            bad = s
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == 'cmd' \
                            and isinstance(v, ast.Constant) \
                            and v.value in commands:
                        bad = v
            if bad is not None \
                    and not mod.suppressed('wire-literal', bad.lineno):
                findings.append(Finding(
                    'wire-literal', rel, bad.lineno,
                    f'cmd:{bad.value}',
                    f'inline command string {bad.value!r} — use '
                    f'serve/protocol.py CMD_* constants so the client, '
                    f'the dispatch, and the vft-wire lock share one '
                    f'spelling'))
    return findings


# -- registry ----------------------------------------------------------------

# the ONE rule registry: name ↔ check function pairs. ALL_CHECKS and
# RULES derive from it, so a rule-name subset (`--rules`, the CI
# contract-gate step) can never silently run the wrong function — two
# hand-aligned parallel tuples would drift exactly that way.
RULE_CHECKS = (
    ('spawn-purity', check_spawn_purity),
    ('recipe-picklable', check_recipe_picklable),
    ('knob-classification', check_knob_classification),
    ('knob-registry', check_knob_registry_single_source),
    ('swallowed-exception', check_swallowed_exceptions),
    ('stdout-purity', check_stdout_purity),
    ('contract-key-sync', check_contract_keys),
    ('stage-vocabulary', check_stage_vocabulary),
    ('thread-discipline', check_thread_discipline),
    ('lock-order', check_lock_order),
    ('wire-literal', check_wire_literal),
)

ALL_CHECKS = tuple(fn for _, fn in RULE_CHECKS)

RULES = tuple(name for name, _ in RULE_CHECKS)


def run_checks(package: Package,
               checks: Iterable = ALL_CHECKS) -> List[Finding]:
    """Raw findings from every check (suppressions NOT applied; repeated
    (file, key) identities NOT yet disambiguated — use :func:`analyze`
    for the baseline-ready view)."""
    findings: List[Finding] = []
    for check in checks:
        findings.extend(check(package))
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.key))
    return findings


def _ordinal_keys(findings: List[Finding]) -> List[Finding]:
    """Disambiguate repeated (file, key) identities with a source-order
    ordinal — stable under line drift, unlike line numbers."""
    seen: Dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.file, f.line)):
        n = seen.get((f.file, f.key), 0)
        seen[(f.file, f.key)] = n + 1
        if n:
            f.key = f'{f.key}#{n + 1}'
    return findings


def analyze(package: Package,
            checks: Iterable = ALL_CHECKS) -> List[Finding]:
    """The baseline-ready view: run every check, drop suppressed
    findings, THEN assign disambiguating ordinals — suppressed siblings
    must not consume ordinals, or deleting one would rename (and
    resurface) a baselined neighbor."""
    from video_features_tpu.analysis.core import filter_suppressed
    return _ordinal_keys(filter_suppressed(package,
                                           run_checks(package, checks)))
