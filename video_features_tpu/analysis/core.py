"""vft-lint core: package model, findings, suppressions, baseline.

The analyzer is deliberately **static**: it parses every module of the
package with :mod:`ast` and never imports any of them. That is what lets
it run in CI before the test lanes, finish in seconds, and keep the one
hard guarantee the spawn-purity rule itself depends on: the analyzer
process never imports jax (``__main__`` enforces it at exit).

Vocabulary:

  * :class:`Module` — one parsed source file: path, AST, source lines,
    and the ``# vft-lint: ok=<rule>`` suppressions found in it;
  * :class:`Package` — every module of one package root (plus an
    optional tests dir, which the contract-key rules read the pinned
    schema sets from);
  * :class:`Finding` — one ``file:line`` report with a stable rule id
    and a stable ``key`` (identity that survives line drift — baselines
    match on ``(rule, file, key)``, never on line numbers);
  * baseline — a JSON list of accepted finding identities. The shipped
    baseline is EMPTY: every pre-existing accepted site carries an
    inline suppression with its rationale instead, so the rationale
    lives next to the code it excuses.

Suppression syntax (same line or the immediately preceding line)::

    except Exception:  # vft-lint: ok=swallowed-exception — teardown
    # vft-lint: ok=stdout-purity — show_pred narration is a stdout surface
    print(...)

Multiple rules separate with commas: ``ok=stdout-purity,swallowed-exception``.
"""
from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(r'#\s*vft-lint:\s*ok=([a-z0-9_,-]+)')

# Exit-code contract shared by every analysis CLI (vft-lint AND
# vft-programs — tools/vft_lint.py, tools/vft_programs.py; CI gates on
# these). EXIT_IMPURE is vft-lint-only: the pure-AST analyzer importing
# jax is a self-violation; vft-programs NEEDS jax by design.
EXIT_CLEAN = 0        # no findings beyond baseline/lock + suppressions
EXIT_ERROR = 1        # analyzer error (unparseable file, bad flags)
EXIT_FINDINGS = 2     # at least one NEW finding / lock drift
EXIT_IMPURE = 3       # the vft-lint analyzer process imported jax

# package-relative files the rules anchor on; a fixture package only
# needs the files its planted rule reads
CONFIG_PY = 'config.py'
CACHE_KEY_PY = 'cache/key.py'
SERVE_SERVER_PY = 'serve/server.py'
SERVE_METRICS_PY = 'serve/metrics.py'
OBS_MANIFEST_PY = 'obs/manifest.py'
TRACING_PY = 'utils/tracing.py'
FARM_WORKER_PY = 'farm/worker.py'
FARM_RECIPES_PY = 'farm/recipes.py'
HOST_TRANSFORMS_PY = 'ops/host_transforms.py'
# the wire surface (vft-wire, analysis/wire.py, + the wire-literal rule):
# the loopback protocol/client and the ingress transport/routes
SERVE_PROTOCOL_PY = 'serve/protocol.py'
SERVE_CLIENT_PY = 'serve/client.py'
INGRESS_HTTP_PY = 'ingress/http.py'
INGRESS_GATEWAY_PY = 'ingress/gateway.py'


class Finding:
    """One rule violation at ``file:line``.

    ``key`` is the drift-stable identity (symbol / import / knob name)
    that baseline matching uses; ``message`` is for humans.
    """

    __slots__ = ('rule', 'file', 'line', 'key', 'message')

    def __init__(self, rule: str, file: str, line: int, key: str,
                 message: str) -> None:
        self.rule = rule
        self.file = file
        self.line = int(line)
        self.key = key
        self.message = message

    @property
    def identity(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.key)

    def render(self, root: Optional[Path] = None) -> str:
        path = self.file if root is None else str(Path(root) / self.file)
        return f'{path}:{self.line}: [{self.rule}] {self.message}'

    def as_json(self) -> Dict[str, str]:
        return {'rule': self.rule, 'file': self.file, 'key': self.key}


class Module:
    """One parsed source file of the package."""

    def __init__(self, rel_path: str, source: str) -> None:
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        # line number → set of rule names suppressed there
        self.suppressions: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                self.suppressions[i] = set(m.group(1).split(','))

    def suppressed(self, rule: str, line: int) -> bool:
        """True if ``rule`` is suppressed at ``line`` — by a trailing
        comment on the line itself or anywhere in the contiguous block
        of comment-only lines directly above it (rationales usually run
        longer than one line)."""
        if rule in self.suppressions.get(line, ()):
            return True
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith('#'):
            # only comment-only lines count going up: a suppression
            # trailing unrelated code must not leak onto the next
            # statement
            if rule in self.suppressions.get(ln, ()):
                return True
            ln -= 1
        return False

    def suppressed_in(self, rule: str, first: int, last: int) -> bool:
        """Marker anywhere in ``[first, last]`` — for findings that span
        a header region (an ``except`` clause whose rationale comment
        leads the handler body)."""
        return any(rule in self.suppressions.get(ln, ())
                   for ln in range(first, last + 1))

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def scope_of(self, node: ast.AST) -> str:
        """Dotted enclosing function/class path of ``node`` (baseline
        keys anchor on this instead of line numbers, so accepted
        findings survive unrelated edits above them)."""
        names: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self.parents.get(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                names.append(cur.name)
        return '.'.join(reversed(names)) or '<module>'


class Package:
    """Every parsed module under one package root.

    ``name`` is the import name the import-graph walker resolves
    absolute imports against (``video_features_tpu`` for the live tree;
    fixtures use their own). ``tests_dir`` — when present — is where the
    contract-key rules read the pinned schema sets from.
    """

    def __init__(self, root: Path, name: str,
                 tests_dir: Optional[Path] = None) -> None:
        self.root = Path(root)
        self.name = name
        self.tests_dir = tests_dir
        self.modules: Dict[str, Module] = {}
        for path in sorted(self.root.rglob('*.py')):
            if '__pycache__' in path.parts:
                continue
            rel = path.relative_to(self.root).as_posix()
            if rel.startswith('analysis/'):
                continue          # the analyzer does not lint itself
            self.modules[rel] = Module(rel, path.read_text())

    def get(self, rel_path: str) -> Optional[Module]:
        return self.modules.get(rel_path)

    def module_name(self, rel_path: str) -> str:
        """Dotted import name of a package-relative file."""
        parts = rel_path[:-3].split('/')          # strip .py
        if parts[-1] == '__init__':
            parts = parts[:-1]
        return '.'.join([self.name] + parts)

    def rel_path_of(self, dotted: str) -> Optional[str]:
        """Inverse of :meth:`module_name` (None for external modules)."""
        if dotted == self.name:
            return '__init__.py' if '__init__.py' in self.modules else None
        prefix = self.name + '.'
        if not dotted.startswith(prefix):
            return None
        rel = dotted[len(prefix):].replace('.', '/')
        for cand in (rel + '.py', rel + '/__init__.py'):
            if cand in self.modules:
                return cand
        return None

    def parse_tests_file(self, filename: str) -> Optional[ast.Module]:
        if self.tests_dir is None:
            return None
        path = Path(self.tests_dir) / filename
        if not path.exists():
            return None
        return ast.parse(path.read_text())


def filter_suppressed(package: Package,
                      findings: Iterable[Finding]) -> List[Finding]:
    out = []
    for f in findings:
        mod = package.get(f.file)
        if mod is not None and mod.suppressed(f.rule, f.line):
            continue
        out.append(f)
    return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path: Path) -> Set[Tuple[str, str, str]]:
    """Accepted finding identities. A missing file is an empty baseline
    (fail closed: every finding is new)."""
    path = Path(path)
    if not path.exists():
        return set()
    data = json.loads(path.read_text() or '[]')
    return {(d['rule'], d['file'], d['key']) for d in data}

def write_baseline(path: Path, findings: Iterable[Finding]) -> None:
    recs = sorted({f.identity for f in findings})
    doc = [{'rule': r, 'file': fl, 'key': k} for r, fl, k in recs]
    Path(path).write_text(json.dumps(doc, indent=1, sort_keys=True) + '\n')


def new_findings(findings: Iterable[Finding],
                 baseline: Set[Tuple[str, str, str]]) -> List[Finding]:
    return [f for f in findings if f.identity not in baseline]


# -- shared AST helpers ------------------------------------------------------

def callable_name(func: ast.AST) -> str:
    """Bare name of a call target: ``Name`` id or ``Attribute`` attr
    (empty for anything fancier) — the one spelling shared by the lint
    rules and the vft-wire extractor."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ''


def module_constants(mod: Optional['Module'],
                     types: tuple = (str, int),
                     prefix: str = '') -> Dict[str, object]:
    """Module-level ``NAME = <constant>`` assignments (bools excluded),
    optionally filtered by name prefix — the constant tables the
    wire-literal rule and vft-wire resolve references against."""
    out: Dict[str, object] = {}
    if mod is None:
        return out
    for stmt in module_level_statements(mod.tree):
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, types) \
                and not isinstance(stmt.value.value, bool):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id.startswith(prefix):
                    out[t.id] = stmt.value.value
    return out


def module_level_statements(tree: ast.Module) -> Iterable[ast.stmt]:
    """Top-level statements, descending into plain ``if`` blocks (version
    gates) but not into function/class bodies."""
    for node in tree.body:
        if isinstance(node, ast.If):
            for sub in list(node.body) + list(node.orelse):
                yield sub
        else:
            yield node


def dict_literal_str_keys(node: ast.AST) -> List[str]:
    """String-constant keys of a dict literal (non-constant keys skipped)."""
    keys: List[str] = []
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.append(k.value)
    return keys


def str_constants_in(node: ast.AST) -> Set[str]:
    """Every string constant anywhere under ``node``."""
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def find_assignment(tree: ast.AST, name: str) -> Optional[ast.AST]:
    """The value node of the (last) module/class-level assignment or
    AnnAssign to ``name``."""
    found = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    found = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == name:
                found = node.value
    return found


def find_function(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def assigned_dict_keys(func: ast.AST, varname: str) -> Set[str]:
    """Keys a function statically gives dict variable ``varname``:
    ``var = {...}`` literal keys plus ``var['k'] = ...`` subscripts."""
    keys: Set[str] = set()
    for node in ast.walk(func):
        targets: List[ast.AST] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == varname:
                keys.update(dict_literal_str_keys(value))
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == varname \
                    and isinstance(t.slice, ast.Constant) \
                    and isinstance(t.slice.value, str):
                keys.add(t.slice.value)
    return keys


def set_literal_values(node: ast.AST) -> Set[str]:
    """String members of a set/frozenset/tuple/list literal, unwrapping
    ``frozenset({...})`` / ``set([...])`` calls."""
    if isinstance(node, ast.Call) and node.args:
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else ''
        if name in ('frozenset', 'set', 'tuple', 'list'):
            node = node.args[0]
    values: Set[str] = set()
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                values.add(el.value)
    return values
