"""Static import-graph walker for the spawn-purity rule.

Builds, per module, the list of import edges with their **level**:

  * ``module`` — executed at import time (top-level statements,
    including version-gate ``if`` blocks);
  * ``function`` — executed lazily when the enclosing function runs.

The spawn closure expands along **module-level** edges transitively,
plus the **function-level** edges of the ROOT modules themselves: a
recipe's lazy helper import (``io.video``, ``extract.streaming``) runs
inside the decoder worker at decode time, so everything those modules
import at module level is part of the worker's real footprint. Deeper
function-level imports are the package's documented *gating* idiom
(``utils/tracing.jax_profiler_trace``) — they exist precisely so the
module can live in a jax-free process — and do not expand the closure.
A *violation* is a module-level import of a forbidden root (jax/flax)
by any module inside the closure.

``if TYPE_CHECKING:`` blocks are skipped entirely: they never execute.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

from video_features_tpu.analysis.core import Module, Package


class ImportEdge(NamedTuple):
    target: str          # dotted module name as written ('jax.numpy')
    line: int
    level: str           # 'module' | 'function'


def _is_type_checking_if(node: ast.If) -> bool:
    test = node.test
    return (isinstance(test, ast.Name) and test.id == 'TYPE_CHECKING') or \
        (isinstance(test, ast.Attribute) and test.attr == 'TYPE_CHECKING')


def _resolve_relative(rel_level: int, pkg_parts: List[str],
                      sub: Optional[str]) -> Optional[str]:
    """Absolute dotted target of a relative import. ``pkg_parts`` is the
    importing module's PACKAGE path — for ``pkg/farm/__init__.py`` that
    is ``pkg.farm`` itself, for ``pkg/farm/worker.py`` it is
    ``pkg.farm`` too (Python resolves level 1 against the containing
    package in both cases; the caller computes this distinction)."""
    if rel_level - 1 > len(pkg_parts):
        return None                          # beyond the top — broken
    base = pkg_parts[:len(pkg_parts) - (rel_level - 1)]
    if sub:
        base = base + [sub]
    return '.'.join(base) if base else None


def _imports_in(body: Iterable[ast.stmt], level: str,
                edges: List[ImportEdge], pkg_parts: List[str]) -> None:
    for node in body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                edges.append(ImportEdge(alias.name, node.lineno, level))
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: resolve against the module's own
                # package path — dropping it would silently shrink the
                # closure and blind the spawn-purity rule
                mod = _resolve_relative(node.level, pkg_parts,
                                        node.module)
                if mod is None:
                    continue
            else:
                mod = node.module or ''
            for alias in node.names:
                # `from pkg.a import b` may bind submodule pkg.a.b — record
                # both; the resolver keeps whichever exists
                edges.append(ImportEdge(f'{mod}.{alias.name}',
                                        node.lineno, level))
            edges.append(ImportEdge(mod, node.lineno, level))
        elif isinstance(node, ast.If):
            if _is_type_checking_if(node):
                continue
            _imports_in(node.body, level, edges, pkg_parts)
            _imports_in(node.orelse, level, edges, pkg_parts)
        elif isinstance(node, (ast.Try, ast.With)):
            for sub_body in ([node.body] +
                             ([h.body for h in node.handlers]
                              if isinstance(node, ast.Try) else []) +
                             ([node.orelse, node.finalbody]
                              if isinstance(node, ast.Try) else [])):
                _imports_in(sub_body, level, edges, pkg_parts)
        elif isinstance(node, ast.ClassDef):
            # class BODIES execute at definition time — an import there
            # runs when the module loads, so it keeps the CURRENT level
            # (methods inside the class drop to 'function' as usual)
            _imports_in(node.body, level, edges, pkg_parts)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _imports_in(node.body, 'function', edges, pkg_parts)
        elif isinstance(node, (ast.For, ast.While)):
            _imports_in(node.body, level, edges, pkg_parts)
            _imports_in(node.orelse, level, edges, pkg_parts)


def module_imports(mod: Module, package: Optional[Package] = None
                   ) -> List[ImportEdge]:
    """Import edges of one module. ``package`` supplies the package
    path relative imports resolve against; without it they are
    dropped."""
    edges: List[ImportEdge] = []
    if package is not None:
        dotted = package.module_name(mod.rel_path)
        # the path level-1 relative imports resolve against: for an
        # __init__.py that is the package ITSELF (module_name already
        # dropped the '__init__' segment); for a plain module, its
        # containing package
        if mod.rel_path.endswith('__init__.py'):
            pkg_parts = dotted.split('.')
        else:
            pkg_parts = dotted.split('.')[:-1]
    else:
        pkg_parts = []
    _imports_in(mod.tree.body, 'module', edges, pkg_parts)
    return edges


def spawn_closure(package: Package, roots: Iterable[str]
                  ) -> Dict[str, Tuple[Optional[str], int]]:
    """Transitive static import closure over intra-package edges.

    Returns ``rel_path → (importer_rel_path, line)`` provenance (roots
    map to ``(None, 0)``), so a violation deep in the graph can name the
    chain that pulled the module in.
    """
    closure: Dict[str, Tuple[Optional[str], int]] = {}
    root_set = {r for r in roots if package.get(r) is not None}
    frontier = list(root_set)
    for r in frontier:
        closure[r] = (None, 0)
    while frontier:
        rel = frontier.pop()
        mod = package.get(rel)
        if mod is None:
            continue
        for edge in module_imports(mod, package):
            if edge.level != 'module' and rel not in root_set:
                continue          # deep lazy imports are the gating idiom
            target_rel = package.rel_path_of(edge.target)
            if target_rel is not None and target_rel not in closure:
                closure[target_rel] = (rel, edge.line)
                frontier.append(target_rel)
    return closure


def chain(closure: Dict[str, Tuple[Optional[str], int]],
          rel: str) -> List[str]:
    """Provenance chain root → ... → rel for messages."""
    out = [rel]
    seen = {rel}
    while True:
        parent, _ = closure.get(out[0], (None, 0))
        if parent is None or parent in seen:
            return out
        out.insert(0, parent)
        seen.add(parent)
