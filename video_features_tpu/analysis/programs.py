"""vft-programs: abstract-interpretation contract checker over compiled
JAX programs.

vft-lint (``analysis/checks.py``) enforces the *Python-level* contracts;
the contracts that actually decide TPU behavior — shapes, dtypes,
sharding, donation, what XLA compiles — live one level down, in the
lowered programs, and nothing else pins them: a silent f64 promotion, a
dropped donation, or a weight tensor accidentally captured by closure
(baked into the HLO as a constant) ships invisibly. This module
AOT-lowers every family's *actual* jitted step — the same callable the
hot paths dispatch — at a canonical abstract geometry, on CPU, at mesh
widths {1, 2} (forced host devices) and, for families that accept a
compute_dtype fast lane, on EVERY lane they accept (``mesh<n>`` =
float32 as always; ``mesh<n>@bfloat16`` for ``registry.BF16_FEATURES``,
whose parameter dtype census proves the transplant cast left no fp32
param behind — the ``bf16-census`` rule; ``mesh<n>@int8`` for
``registry.INT8_FEATURES``, whose census proves the weight quantization
ran and fp32 is the declared minority — the ``int8-census`` rule), and

  * extracts an **abstract signature** per program: batch/output avals
    (weak types included), the full parameter dtype census, the declared
    donated-buffer set, data-axis sharding (``mhlo.num_partitions``),
    ``cost_analysis`` FLOPs/bytes, baked-constant bytes, and a sha256
    of the StableHLO text;
  * runs **rule checks** over the lowering (catalog in
    ``docs/static_analysis.md``): no-f64, no-weak-type leak on outputs,
    no host callback in hot programs, donation-as-declared on the batch
    input, batch-dim shardability at every supported mesh width
    (``parallel.mesh.shard_error``), and a baked-constant budget;
  * **diffs** the live signatures against the committed
    ``PROGRAMS.lock.json`` and exits 0 clean / 2 on drift or a new rule
    finding (``--write-lock`` re-pins intentionally) — mirroring
    vft-lint's exit-code conventions. Suppressions mirror vft-lint's
    rationale-at-the-site convention, but live in the family's
    ``program_specs`` (``ProgramSpec(ok={rule: rationale})``) because a
    finding names a *program*, not a source line.

No device execution happens: lowering is trace + StableHLO emission,
and the cost analysis runs on the unoptimized module. The whole check
(8 families × 2 widths) completes in well under two minutes on a laptop
CPU, which is what lets CI gate on it.

Everything here imports jax lazily: the module itself stays importable
in jax-free processes (the manifest's lock-hash recording and the lock
readers below are pure stdlib).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import re
import sys
from pathlib import Path
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

from video_features_tpu.analysis.core import (
    EXIT_CLEAN, EXIT_ERROR, EXIT_FINDINGS,
)
from video_features_tpu.config import KNOWN_FEATURE_TYPES

LOCK_SCHEMA = 'video_features_tpu.programs_lock/1'
DEFAULT_LOCK = 'PROGRAMS.lock.json'           # repo-root, committed

# every family the lock must cover — the ONE registry of feature types
# (config.py), not a second hand-synced list: a new family is a lock
# gap (and a checker 'coverage' finding) the day it lands
FAMILIES = tuple(KNOWN_FEATURE_TYPES)
# non-extractor program providers the lock ALSO pins: the feature
# index's query program is a shipped compiled program like any step
# function, so it rides the same gate (float32 lane only — it is not a
# feature family and never joins registry.BF16_FEATURES)
EXTRA_PROGRAMS = ('index',)
ALL_PINNED = FAMILIES + EXTRA_PROGRAMS
MESH_WIDTHS = (1, 2)

# compute_dtype lanes the lock pins per family: 'float32' entries keep
# their historical mesh<n> keys byte-for-byte (the default path must
# never drift when a lane is added), fast-lane variants land under
# mesh<n>@<lane> for every family in the lane's registry opt-in set —
# their parameter dtype census is the per-lane proof the storage
# transform actually happened: 'bfloat16' (registry.BF16_FEATURES) must
# carry ZERO fp32 params (the bf16-census rule below), 'int8'
# (registry.INT8_FEATURES) must carry int8 weight payloads with float32
# reduced to the DECLARED minority — scales, biases, norm params
# (the int8-census rule below).
LANES = ('float32', 'bfloat16', 'int8')

RULES = ('no-f64', 'no-weak-type', 'no-host-callback', 'donation',
         'shardable', 'const-budget', 'bf16-census', 'int8-census')


def lane_families(lane: str, families: Iterable[str]) -> tuple:
    """The subset of ``families`` that builds on ``lane`` — every family
    for float32; only the lane's registry opt-in set for the fast lanes
    (``BF16_FEATURES`` / ``INT8_FEATURES`` — the rest REFUSE the knob at
    config time, which is itself contract-tested, not a lock gap)."""
    if lane == 'float32':
        return tuple(families)
    from video_features_tpu.registry import BF16_FEATURES, INT8_FEATURES
    accepted = BF16_FEATURES if lane == 'bfloat16' else INT8_FEATURES
    return tuple(f for f in families if f in accepted)


def mesh_key(width: int, lane: str) -> str:
    """Lock entry key for one (mesh width, compute_dtype lane):
    ``mesh<n>`` for float32 (unchanged — pre-lane locks stay valid),
    ``mesh<n>@bfloat16`` for the fast lane."""
    return f'mesh{width}' if lane == 'float32' else f'mesh{width}@{lane}'


def parse_mesh_key(key: str) -> Tuple[int, str]:
    """Inverse of :func:`mesh_key`: ``'mesh2@bfloat16'`` → (2, 'bfloat16')."""
    base, _, lane = key.partition('@')
    try:
        width = int(base.replace('mesh', '') or 0)
    except ValueError:
        width = 0
    return width, (lane or 'float32')

# default baked-constant budget per program: small epilogue constants
# (normalization mean/std, resize index tables, iota caches) are fine;
# a real weight tensor folded into the HLO is megabytes — the failure
# this rule exists for (closure capture instead of params threading)
CONST_BUDGET = 1 << 20

# StableHLO custom_call targets that mean "the program calls back into
# the host python process" — a hot program stalling on the GIL
_CALLBACK_MARKERS = ('callback', 'py_func')


# -- family build recipes ----------------------------------------------------

# overrides that make every family buildable on a jax-CPU host with no
# checkpoints and no video files: the lock pins PROGRAM signatures, and
# random weights have exactly the shapes/dtypes real checkpoints
# transplant to (tests/test_transplant.py holds that equivalence)
_BASE_OVERRIDES: Dict[str, Any] = {
    'device': 'cpu',
    'video_paths': ['__programs_check__.mp4'],
    'allow_random_weights': True,
    'compilation_cache_dir': None,
}
_FAMILY_OVERRIDES: Dict[str, Dict[str, Any]] = {
    # the registry arch the timm lane is tuned around; pretrained=False
    # skips the pip-timm download path (shapes come from the native init)
    'timm': {'model_name': 'vit_base_patch16_224', 'pretrained': False},
}


def build_family(feature_type: str, compute_dtype: str = 'float32'):
    """The real extractor, built exactly like production builds it
    (``registry.create_extractor`` over the merged config) — so the
    lowered programs ARE the shipped programs, closures included.
    ``compute_dtype`` selects the lane (``'bfloat16'`` builds the fast
    lane's extractor: bf16 params from the transplant cast, bf16
    activations — whose lowering the mesh<n>@bfloat16 lock variants
    pin)."""
    if feature_type == 'index':
        # the feature index's query program: no extractor, no weights —
        # the provider lowers the SAME jitted callable the serve query
        # path dispatches, at the canonical lock geometry
        from video_features_tpu.index.search import IndexPrograms
        return IndexPrograms()
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    overrides = dict(_BASE_OVERRIDES)
    overrides.update(_FAMILY_OVERRIDES.get(feature_type, {}))
    if compute_dtype != 'float32':
        overrides['compute_dtype'] = compute_dtype
    return create_extractor(load_config(feature_type, overrides=overrides))


# -- program specs -----------------------------------------------------------

class ProgramSpec:
    """One abstract AOT program a family exposes to the checker.

    ``jitted`` must be the SAME jit-wrapped callable the hot path
    dispatches (not a re-wrap): the baked-constant rule exists precisely
    to catch what the real callable closes over. ``args``/``kwargs``
    are abstract (``jax.ShapeDtypeStruct``) inputs at the family's
    canonical lock geometry; ``batch_argnum`` names the positional arg
    that is the device batch (donation + shardability anchor on it).
    ``ok`` maps accepted rule ids to their rationale — the vft-programs
    analog of vft-lint's inline ``# vft-lint: ok=<rule>`` suppression,
    living in the family source next to the spec it excuses.
    """

    __slots__ = ('name', 'jitted', 'args', 'kwargs', 'batch_argnum',
                 'donate_batch', 'const_budget', 'ok')

    def __init__(self, name: str, jitted, args: Tuple, kwargs=None, *,
                 batch_argnum: int = 1, donate_batch: bool = False,
                 const_budget: int = CONST_BUDGET,
                 ok: Optional[Mapping[str, str]] = None) -> None:
        self.name = name
        self.jitted = jitted
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})
        self.batch_argnum = int(batch_argnum)
        self.donate_batch = bool(donate_batch)
        self.const_budget = int(const_budget)
        self.ok = dict(ok or {})


class Finding:
    """One rule violation or lock drift at
    ``family/mesh<n>[@lane]/program``."""

    __slots__ = ('rule', 'family', 'mesh', 'program', 'message', 'lane')

    def __init__(self, rule: str, family: str, mesh: int, program: str,
                 message: str, lane: str = 'float32') -> None:
        self.rule = rule
        self.family = family
        self.mesh = int(mesh)
        self.program = program
        self.message = message
        self.lane = lane

    def render(self) -> str:
        lane = '' if self.lane == 'float32' else f'@{self.lane}'
        return (f'{self.family}/mesh{self.mesh}{lane}/{self.program}: '
                f'[{self.rule}] {self.message}')


# -- shared abstract-lowering seam (obs/manifest.py reuses this) -------------

def stablehlo_sha256(text: str) -> str:
    """The byte-deterministic program identity: sha256 over the lowered
    StableHLO text. The ONE home of the hashing convention — the lock
    entries pin it per (family, mesh-width, lane), and the executable
    store (``aot/runtime.py``) keys its persisted compiled executables
    by the same identity, which is what makes an unchanged lock imply a
    compile-free boot. (The store hashes the PRODUCTION lowering, which
    additionally bakes the ambient matmul-precision context and the
    live args' shardings that the checker's abstract lowering carries
    no opinion on — same identity space, same determinism guarantee.)"""
    return hashlib.sha256(text.encode()).hexdigest()


def abstract_lowering(jitted, *args, **kwargs):
    """AOT-lower ``jitted`` at the abstract shapes of ``args``/``kwargs``
    — concrete arrays are mapped to ``ShapeDtypeStruct`` in place, avals
    pass through. The one home of the ``jitted.lower(...)`` seam: the
    run manifest's cost analysis and the vft-programs signature
    extraction both go through here."""
    import jax
    shaped = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, 'shape') and not isinstance(x, jax.ShapeDtypeStruct)
        else x, (args, kwargs))
    return jitted.lower(*shaped[0], **shaped[1])


def lowering_cost(lowered) -> Optional[Dict[str, float]]:
    """FLOPs / bytes-accessed of a lowering (unoptimized-module cost
    analysis — no compile). None when the backend doesn't support it."""
    try:
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not cost:
            return None
        out = {}
        for key in ('flops', 'bytes accessed'):
            if key in cost:
                out[key.replace(' ', '_')] = float(cost[key])
        return out or None
    except Exception:
        # vft-lint: ok=swallowed-exception — cost analysis is an
        # optimization report, never a requirement (manifest contract)
        return None


# -- signature extraction ----------------------------------------------------

def _aval_doc(aval) -> Dict[str, Any]:
    doc: Dict[str, Any] = {'shape': [int(d) for d in aval.shape],
                           'dtype': str(aval.dtype)}
    if getattr(aval, 'weak_type', False):
        doc['weak_type'] = True
    return doc


def _param_census(tree) -> Dict[str, Dict[str, int]]:
    """dtype → {arrays, bytes} over every array leaf of ``tree`` — the
    full parameter dtype census the precision lanes diff against."""
    import jax
    import numpy as np
    census: Dict[str, Dict[str, int]] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, 'shape'):
            continue
        dt = str(leaf.dtype)
        rec = census.setdefault(dt, {'arrays': 0, 'bytes': 0})
        rec['arrays'] += 1
        rec['bytes'] += int(np.prod(leaf.shape, dtype=np.int64)
                            * np.dtype(leaf.dtype).itemsize)
    return census


def _donated_flags(lowered) -> List[bool]:
    """Per-positional-arg declared donation (True when ANY leaf of the
    arg is donated). ``args_info`` reflects the jit's declaration even
    on backends that drop donation at compile time (CPU). Its structure
    mirrors the call: ``(args, kwargs)``."""
    import jax
    info = lowered.args_info
    positional = info[0] if (isinstance(info, tuple) and len(info) == 2
                             and isinstance(info[1], dict)) else info
    flags = []
    for arg in positional:
        leaves = jax.tree_util.tree_leaves(
            arg, is_leaf=lambda x: hasattr(x, 'donated'))
        flags.append(any(getattr(leaf, 'donated', False)
                         for leaf in leaves))
    return flags


_NUM_PARTITIONS_RE = re.compile(r'mhlo.num_partitions = (\d+)')


def program_signature(spec: ProgramSpec) -> Dict[str, Any]:
    """The abstract signature of one program — everything the lock pins.

    One trace, one lowering: ``jitted.trace(...)`` (the jax AOT stage)
    respects the jit's static argnames — which ``jax.eval_shape`` /
    ``jax.make_jaxpr`` would not — and its ClosedJaxpr carries both the
    weak-typed output avals and the closed-over consts."""
    import jax
    traced = spec.jitted.trace(*spec.args, **spec.kwargs)
    lowered = traced.lower()
    text = lowered.as_text()
    batch = spec.args[spec.batch_argnum]
    donated = _donated_flags(lowered)
    m = _NUM_PARTITIONS_RE.search(text)
    sig: Dict[str, Any] = {
        'batch': _aval_doc(batch),
        'params': _param_census(spec.args[0]),
        'out': [_aval_doc(a) for a in traced.jaxpr.out_avals],
        'out_tree': str(jax.tree_util.tree_structure(traced.out_info)),
        'batch_donated': bool(donated[spec.batch_argnum]
                              if spec.batch_argnum < len(donated) else False),
        'donated_args': [i for i, d in enumerate(donated) if d],
        'num_partitions': int(m.group(1)) if m else 1,
        'stablehlo_sha256': stablehlo_sha256(text),
    }
    cost = lowering_cost(lowered)
    if cost:
        sig['cost'] = {k: int(v) for k, v in cost.items()}
    # bytes the program CLOSES OVER (vs. takes as args): a large value
    # means weights were captured by closure and get baked into the
    # compiled HLO on every geometry. Recorded at EVERY width — the
    # jaxpr is already built, and width-conditional fields would make a
    # --mesh-widths subset run drift against a full-width lock.
    sig['const_bytes'] = int(sum(getattr(c, 'nbytes', 0)
                                 for c in traced.jaxpr.consts))
    # keep the text around for the rule pass without re-lowering
    sig['_text'] = text
    return sig


# -- rule checks -------------------------------------------------------------

def check_program(spec: ProgramSpec, sig: Dict[str, Any], family: str,
                  width: int, mesh, lane: str = 'float32') -> List[Finding]:
    findings: List[Finding] = []
    text = sig['_text']

    def report(rule: str, message: str) -> None:
        if rule not in spec.ok:
            findings.append(Finding(rule, family, width, spec.name,
                                    message, lane=lane))

    if re.search(r'\bf64\b|xf64[>x]', text):
        report('no-f64',
               'lowered program contains f64 ops — a silent float64 '
               'promotion crossed the host/device boundary (pin float32 '
               'at the boundary; the MXU has no f64 path)')
    for i, out in enumerate(sig['out']):
        if out.get('weak_type'):
            report('no-weak-type',
                   f'output leaf {i} has a weak type ({out["dtype"]}) — '
                   f'a python-scalar-only epilogue leaked; downstream '
                   f'dtype promotion becomes context-dependent')
    for marker in _CALLBACK_MARKERS:
        if marker in text:
            report('no-host-callback',
                   f'lowered program contains a host-callback custom '
                   f'call ({marker!r}) — a hot program must never stall '
                   f'device steps on the python GIL')
            break
    if sig['batch_donated'] != spec.donate_batch:
        want = 'donated' if spec.donate_batch else 'NOT donated'
        got = 'donated' if sig['batch_donated'] else 'not donated'
        report('donation',
               f'batch input declared {want} by the family spec but the '
               f'jitted program has it {got} — donation drift changes '
               f'device-memory behavior silently')
    if mesh is not None:
        from video_features_tpu.parallel.mesh import shard_error
        batch_len = sig['batch']['shape'][0]
        err = shard_error(batch_len, mesh)
        if err is not None:
            report('shardable', f'batch dim not shardable at mesh width '
                                f'{width}: {err}')
    if sig.get('const_bytes', 0) > spec.const_budget:
        report('const-budget',
               f'program closes over {sig["const_bytes"]} bytes of '
               f'constants (budget {spec.const_budget}) — weights '
               f'captured by closure get baked into the HLO per '
               f'geometry instead of being passed as params')
    if lane == 'bfloat16':
        # the lane's load-bearing proof: the transplant-time cast left
        # no fp32 (or fp64) PARAM behind — a survivor would silently
        # keep fp32 HBM residency and promote its whole sub-graph back
        # to fp32, defeating the knob while the bench still reports a
        # "bf16" number. fp32 is allowed only in ACTIVATION islands
        # (ops/nn.py), which a params census never sees.
        leaked = sorted(dt for dt in sig['params']
                        if dt in ('float32', 'float64'))
        if leaked:
            detail = ', '.join(
                f'{dt}: {sig["params"][dt]["arrays"]} array(s) / '
                f'{sig["params"][dt]["bytes"]} bytes' for dt in leaked)
            report('bf16-census',
                   f'compute_dtype=bfloat16 program still carries '
                   f'{detail} in its parameter census — the '
                   f'transplant-time cast (torch2jax dtype seam) missed '
                   f'them; bf16 params must be bf16 in HBM')
    if lane == 'int8':
        # the int8 lane's proof, same shape as bf16's but with a
        # DECLARED fp32 minority: weights dominate a model's bytes, so
        # after quantization (ops/quant.py) the census must show int8
        # payloads outweighing the fp32 leftovers (per-channel scales,
        # biases, norm params, embedding tables). fp32 bytes >= int8
        # bytes means the quantizer missed the weights — full-size HBM
        # residency under an "int8" label.
        census = sig['params']
        if 'float64' in census:
            report('int8-census',
                   'compute_dtype=int8 program carries float64 params — '
                   'no lane stores f64')
        if 'int8' not in census:
            report('int8-census',
                   'compute_dtype=int8 program has NO int8 params in '
                   'its census — the transplant-time quantization '
                   '(ops/quant.py via the torch2jax dtype seam) never '
                   'ran')
        else:
            f32 = census.get('float32', {}).get('bytes', 0)
            i8 = census['int8']['bytes']
            if f32 >= i8:
                report('int8-census',
                       f'compute_dtype=int8 program carries more float32 '
                       f'param bytes ({f32}) than int8 ({i8}) — float32 '
                       f'must be the declared minority (scales/biases/'
                       f'norm params); the quantizer missed the weights')
    return findings


# -- collection --------------------------------------------------------------

def _program_mesh(width: int):
    """Data-only mesh of ``width`` host devices (None for width 1 — the
    single-device programs carry no sharding annotations)."""
    if width <= 1:
        return None
    from video_features_tpu.parallel.mesh import make_mesh
    return make_mesh(n_devices=width, time_parallel=1)


def collect(families: Iterable[str], widths: Iterable[int],
            lanes: Iterable[str] = LANES,
            ) -> Tuple[Dict[str, Any], List[Finding]]:
    """Build each family once per lane it supports, lower its programs
    at every width, run the rule checks. Returns (live lock document
    fragment, findings). float32 entries land under the historical
    ``mesh<n>`` keys; bf16-lane entries (``registry.BF16_FEATURES``
    only) under ``mesh<n>@bfloat16``."""
    families = tuple(families)
    live: Dict[str, Any] = {}
    findings: List[Finding] = []
    for family in families:
        live[family] = {}
    for lane in lanes:
        for family in lane_families(lane, families):
            ex = build_family(family, compute_dtype=lane)
            fam_doc = live[family]
            for width in widths:
                mesh = _program_mesh(width)
                specs = ex.program_specs(mesh=mesh)
                if not specs:
                    findings.append(Finding(
                        'coverage', family, width, '-',
                        f'{family} exposes no abstract program specs '
                        f'(BaseExtractor.program_specs) — every family '
                        f'must pin its compiled programs', lane=lane))
                    continue
                progs: Dict[str, Any] = {}
                for spec in specs:
                    sig = program_signature(spec)
                    findings.extend(
                        check_program(spec, sig, family, width, mesh,
                                      lane=lane))
                    sig.pop('_text')
                    progs[spec.name] = sig
                fam_doc[mesh_key(width, lane)] = {'programs': progs}
    return live, findings


# -- the lock ----------------------------------------------------------------

def default_lock_path() -> Path:
    """Repo-root ``PROGRAMS.lock.json`` (the package's parent)."""
    return Path(__file__).resolve().parent.parent.parent / DEFAULT_LOCK


def load_lock(path) -> Dict[str, Any]:
    path = Path(path)
    if not path.exists():
        return {}
    return json.loads(path.read_text() or '{}')


def write_lock(path, live: Dict[str, Any], *,
               prune_families: bool = False,
               replace_widths: bool = False) -> None:
    """Re-pin: replace exactly the checked (family, mesh width) entries,
    keep the rest — a ``--families`` subset must not drop sibling
    families, and a ``--mesh-widths`` subset must not drop the family's
    OTHER widths' pinned signatures.

    A FULL-scope re-pin (the bare ``--write-lock``) also prunes what
    drift findings point at: ``prune_families`` drops lock families that
    are no longer known (so the 'unknown family' finding's own
    remediation advice actually remediates), and ``replace_widths``
    replaces each checked family's entry wholesale (stale ``mesh<n>``
    keys from a retired width don't accrete silently)."""
    doc = load_lock(path)
    families = dict(doc.get('families', {}))
    if prune_families:
        families = {k: v for k, v in families.items() if k in ALL_PINNED}
    for family, fam_doc in live.items():
        if replace_widths:
            families[family] = {k: fam_doc[k] for k in sorted(fam_doc)}
            continue
        merged = dict(families.get(family, {}))
        merged.update(fam_doc)
        families[family] = {k: merged[k] for k in sorted(merged)}
    out = {
        'schema': LOCK_SCHEMA,
        'families': {k: families[k] for k in sorted(families)},
    }
    Path(path).write_text(json.dumps(out, indent=1, sort_keys=True) + '\n')


def family_lock_hashes(feature_type: str,
                       path=None) -> Dict[str, Dict[str, str]]:
    """``{mesh<n>: {program: stablehlo_sha256}}`` for one family from the
    committed lock — pure stdlib (no jax), safe from any process. The
    run manifest records this so a production trace names exactly which
    pinned program ran. ``{}`` when the lock is absent or the family is
    unpinned."""
    try:
        doc = load_lock(path or default_lock_path())
    except Exception:
        # vft-lint: ok=swallowed-exception — telemetry never fails a
        # run: an unreadable/corrupt lock reads as "unpinned"
        return {}
    fam = doc.get('families', {}).get(feature_type, {})
    out: Dict[str, Dict[str, str]] = {}
    for mesh, entry in fam.items():
        progs = entry.get('programs', {})
        hashes = {name: sig.get('stablehlo_sha256', '')
                  for name, sig in progs.items()}
        if hashes:
            out[mesh] = hashes
    return out


# fields whose drift is reported individually (everything else in the
# signature rides along under the stablehlo hash)
_DIFF_FIELDS = ('batch', 'params', 'out', 'out_tree', 'batch_donated',
                'donated_args', 'num_partitions', 'const_bytes', 'cost',
                'stablehlo_sha256')


def diff_lock(live: Dict[str, Any], lock: Dict[str, Any],
              checked: Iterable[str],
              widths: Iterable[int] = MESH_WIDTHS,
              lanes: Iterable[str] = LANES) -> List[Finding]:
    """Field-by-field drift between the live lowerings and the lock.
    Families outside ``checked`` — and mesh widths outside ``widths`` /
    lanes outside ``lanes`` — are skipped (a ``--families`` /
    ``--mesh-widths`` / ``--lanes`` subset run must not report what it
    didn't lower as missing/stale); but a lock family that is not a
    known family at all is always reported. A bf16 lane key is only
    "checked" for families that ACCEPT the lane — a lock carrying
    mesh<n>@bfloat16 for a refusing family is stale and surfaces as a
    live-side-missing program drift once the family joins the lane's
    checked set... until then it is simply never compared (subset
    semantics), so prune it with a full-scope --write-lock."""
    findings: List[Finding] = []
    lanes = tuple(lanes)
    locked = lock.get('families', {})

    def checked_meshes(family: str) -> set:
        return {mesh_key(w, lane) for w in widths for lane in lanes
                if family in lane_families(lane, (family,))}
    for family in sorted(locked):
        if family not in ALL_PINNED:
            findings.append(Finding(
                'lock-drift', family, 0, '-',
                f'lock names unknown family {family!r} — stale entry '
                f'(re-pin with --write-lock)'))
    for family in checked:
        lv = live.get(family, {})
        lk = locked.get(family)
        if lk is None:
            findings.append(Finding(
                'lock-drift', family, 0, '-',
                f'{family} is not in the lock — pin it with '
                f'--write-lock'))
            continue
        for mesh in sorted((set(lv) | set(lk)) & checked_meshes(family)):
            width, lane = parse_mesh_key(mesh)
            lvp = lv.get(mesh, {}).get('programs', {})
            lkp = lk.get(mesh, {}).get('programs', {})
            for name in sorted(set(lvp) | set(lkp)):
                if name not in lkp:
                    findings.append(Finding(
                        'lock-drift', family, width, name,
                        'new program not in the lock (compiled-program '
                        'count changed) — re-pin with --write-lock',
                        lane=lane))
                    continue
                if name not in lvp:
                    findings.append(Finding(
                        'lock-drift', family, width, name,
                        'pinned program no longer lowered by the family '
                        '— stale lock entry (re-pin with --write-lock)',
                        lane=lane))
                    continue
                for field in _DIFF_FIELDS:
                    a, b = lkp[name].get(field), lvp[name].get(field)
                    if a is None and b is None:
                        continue
                    if a != b:
                        findings.append(Finding(
                            'lock-drift', family, width, name,
                            f'{field} drifted: lock={_short(a)} '
                            f'live={_short(b)}', lane=lane))
    return findings


def _short(v: Any, n: int = 120) -> str:
    s = json.dumps(v, sort_keys=True) if not isinstance(v, str) else v
    return s if len(s) <= n else s[:n - 1] + '…'


# -- CLI ---------------------------------------------------------------------

def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog='vft-programs',
        description='abstract-interpretation contract checker over every '
                    'compiled JAX program (docs/static_analysis.md)')
    parser.add_argument('--families', help='comma-separated subset '
                        f'(default: all — {",".join(ALL_PINNED)})')
    parser.add_argument('--mesh-widths', default='1,2',
                        help='comma-separated mesh widths to pin '
                        '(default: 1,2 — width 2 needs '
                        '--xla_force_host_platform_device_count=2)')
    parser.add_argument('--lanes', default=','.join(LANES),
                        help='comma-separated compute_dtype lanes to '
                        'check/pin (default: float32,bfloat16,int8 — '
                        'each fast lane covers only its registry opt-in '
                        'set, BF16_FEATURES / INT8_FEATURES)')
    parser.add_argument('--lock', help='lock file path (default: '
                        f'<repo>/{DEFAULT_LOCK})')
    parser.add_argument('--write-lock', action='store_true',
                        help='re-pin: write the live signatures for the '
                        'checked families and exit 0')
    parser.add_argument('--list-rules', action='store_true')
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES:
            print(rule)
        return EXIT_CLEAN

    families = tuple(args.families.split(',')) if args.families \
        else ALL_PINNED
    unknown = [f for f in families if f not in ALL_PINNED]
    if unknown:
        print(f'vft-programs: unknown families {unknown} '
              f'(known: {", ".join(ALL_PINNED)})', file=sys.stderr)
        return EXIT_ERROR
    widths = tuple(int(w) for w in args.mesh_widths.split(','))
    lanes = tuple(args.lanes.split(','))
    bad_lanes = [lane for lane in lanes if lane not in LANES]
    if bad_lanes:
        print(f'vft-programs: unknown lanes {bad_lanes} '
              f'(known: {", ".join(LANES)})', file=sys.stderr)
        return EXIT_ERROR
    lock_path = Path(args.lock) if args.lock else default_lock_path()

    import jax
    n_local = len(jax.devices())
    if max(widths) > n_local:
        print(f'vft-programs: mesh width {max(widths)} needs '
              f'{max(widths)} host devices but jax sees {n_local} — run '
              f'via tools/vft_programs.py (it forces '
              f'XLA_FLAGS=--xla_force_host_platform_device_count), or '
              f'set the flag before jax initializes', file=sys.stderr)
        return EXIT_ERROR

    try:
        live, findings = collect(families, widths, lanes)
    except Exception as e:                    # noqa: BLE001 — CLI boundary
        import traceback
        traceback.print_exc()
        print(f'vft-programs: analyzer error: {e}', file=sys.stderr)
        return EXIT_ERROR

    if args.write_lock:
        write_lock(lock_path, live,
                   prune_families=set(families) == set(ALL_PINNED),
                   replace_widths=(set(widths) == set(MESH_WIDTHS)
                                   and set(lanes) == set(LANES)))
        n = sum(len(e.get('programs', {}))
                for fam in live.values() for e in fam.values())
        print(f'vft-programs: pinned {n} program signature(s) across '
              f'{len(live)} family(ies) to {lock_path}')
        for f in findings:
            print(f'(unpinnable) {f.render()}', file=sys.stderr)
        return EXIT_CLEAN

    findings.extend(diff_lock(live, load_lock(lock_path), families,
                              widths=widths, lanes=lanes))
    for f in findings:
        print(f.render())
    n_progs = sum(len(e.get('programs', {}))
                  for fam in live.values() for e in fam.values())
    print(f'vft-programs: {len(findings)} finding(s) across {n_progs} '
          f'programs, {len(live)} families, mesh widths '
          f'{list(widths)}, lanes {list(lanes)}', file=sys.stderr)
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == '__main__':
    sys.exit(main())
