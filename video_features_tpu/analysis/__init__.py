"""vft-lint: AST/import-graph invariant checker for this codebase.

``python -m video_features_tpu.analysis`` (or ``tools/vft_lint.py``)
parses the whole package with :mod:`ast` — never importing it — and
enforces the contracts the repo states in prose but previously checked
nowhere: spawn-worker jax-freedom, the knob-classification registry,
no silently swallowed exceptions, stdout purity, export-schema /
stage-vocabulary sync, recipe picklability, and thread-discipline
declarations. Rule catalog and suppression syntax:
``docs/static_analysis.md``.
"""
from video_features_tpu.analysis.checks import (
    ALL_CHECKS, RULES, analyze, run_checks,
)
from video_features_tpu.analysis.core import (
    Finding, Module, Package, filter_suppressed, load_baseline,
    new_findings, write_baseline,
)

__all__ = [
    'ALL_CHECKS', 'RULES', 'analyze', 'run_checks', 'Finding', 'Module',
    'Package', 'filter_suppressed', 'load_baseline', 'new_findings',
    'write_baseline',
]
