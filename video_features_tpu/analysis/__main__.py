"""vft-lint CLI: ``python -m video_features_tpu.analysis``.

Exit-code contract (CI gates on it — .github/workflows/ci.yml ``lint``
job):

  0  no findings beyond the baseline (and beyond inline suppressions)
  1  analyzer error (unparseable file, bad flags)
  2  at least one NEW finding
  3  the analyzer process imported jax (self-violation: the lint must
     be runnable on a jax-free host and must never pay XLA startup)

There is deliberately no ``--fix``: every fix is a reviewed code change.
``--write-baseline`` exists for adopting the suite on a dirty tree; this
repo ships an EMPTY baseline — accepted sites carry inline
``# vft-lint: ok=<rule>`` suppressions with their rationale instead.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from video_features_tpu.analysis.checks import (
    ALL_CHECKS, RULE_CHECKS, RULES, analyze, closure_forbidden_imports,
)
from video_features_tpu.analysis.core import (
    EXIT_CLEAN, EXIT_ERROR, EXIT_FINDINGS, EXIT_IMPURE, Package,
    load_baseline, new_findings, write_baseline,
)

DEFAULT_BASELINE = 'tools/vft_lint_baseline.json'

# The purity contract is about what the ANALYZER pulls in: a host
# process (pytest with a jax-using conftest) may legitimately embed
# main() with jax already loaded — only an import that appears during
# the run is a self-violation. CAVEAT: under `python -m`, the parent
# package __init__ (config.py, registry.py) executes before this
# module, so a jax import sneaking into THAT chain would read as
# "preloaded" here. tools/vft_lint.py closes the gap: it snapshots
# sys.modules BEFORE importing anything of the package and passes the
# honest value via `jax_preloaded` — which is why the CI lint job's
# strong exit-3 guarantee is tested through the wrapper.
_JAX_PRELOADED = 'jax' in sys.modules


def _default_roots():
    pkg_root = Path(__file__).resolve().parent.parent
    repo_root = pkg_root.parent
    tests_dir = repo_root / 'tests'
    return pkg_root, tests_dir if tests_dir.is_dir() else None, repo_root


def main(argv=None, jax_preloaded=None) -> int:
    parser = argparse.ArgumentParser(
        prog='vft-lint',
        description='AST/import-graph invariant checker for '
                    'video_features_tpu (docs/static_analysis.md)')
    parser.add_argument('--root', help='package root to analyze '
                        '(default: the installed video_features_tpu/)')
    parser.add_argument('--package-name', default='video_features_tpu',
                        help='import name absolute imports resolve '
                        'against (fixture trees use their own)')
    parser.add_argument('--tests-dir', help='directory holding the '
                        'pinned contract sets (default: <repo>/tests)')
    parser.add_argument('--baseline', help='accepted-findings file '
                        f'(default: <repo>/{DEFAULT_BASELINE})')
    parser.add_argument('--write-baseline', action='store_true',
                        help='accept every current finding and exit 0')
    parser.add_argument('--fail-on-new', action='store_true',
                        help='exit 2 on findings not in the baseline '
                        '(the default behavior, spelled out for CI)')
    parser.add_argument('--rules', help='comma-separated subset of rules '
                        'to run (default: all) — CI uses this to name a '
                        'specific gate (e.g. contract-key-sync) in its '
                        'own step instead of burying it')
    parser.add_argument('--list-rules', action='store_true')
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in RULES:
            print(rule)
        return EXIT_CLEAN

    pkg_root, tests_dir, repo_root = _default_roots()
    if args.root:
        pkg_root = Path(args.root)
        tests_dir = None
        repo_root = pkg_root.parent
    if args.tests_dir:
        tests_dir = Path(args.tests_dir)
    baseline_path = Path(args.baseline) if args.baseline \
        else repo_root / DEFAULT_BASELINE

    checks = ALL_CHECKS
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(',') if r.strip()}
        unknown = wanted - set(RULES)
        if unknown:
            print(f'vft-lint: unknown rule(s) {sorted(unknown)}; '
                  f'known: {", ".join(RULES)}', file=sys.stderr)
            return EXIT_ERROR
        checks = tuple(check for name, check in RULE_CHECKS
                       if name in wanted)

    try:
        package = Package(pkg_root, args.package_name, tests_dir=tests_dir)
        findings = analyze(package, checks)
    except SyntaxError as e:
        print(f'vft-lint: parse error: {e}', file=sys.stderr)
        return EXIT_ERROR

    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f'vft-lint: wrote {len(findings)} accepted finding(s) to '
              f'{baseline_path}')
        return EXIT_CLEAN

    fresh = new_findings(findings, load_baseline(baseline_path))
    for f in fresh:
        print(f.render(pkg_root))
    known = len(findings) - len(fresh)
    status = (f'vft-lint: {len(fresh)} new finding(s)'
              + (f', {known} baselined' if known else '')
              + f' across {len(package.modules)} modules')
    print(status, file=sys.stderr)

    # self-enforcement: the analyzer's own purity contract, two ways.
    # (a) STATIC, preload-proof: the import chain `python -m` traverses
    # before this module runs (package __init__ -> config/registry) must
    # never gain a module-level jax import — checked on the AST of the
    # INSTALLED package, so it trips even on hosts where jax is already
    # resident and the dynamic probe below reads "preloaded".
    own_pkg_root, own_tests, _ = _default_roots()
    own = package if pkg_root == own_pkg_root else \
        Package(own_pkg_root, 'video_features_tpu', tests_dir=own_tests)
    chain_violations = closure_forbidden_imports(
        own, ('__init__.py',), 'analyzer-purity',
        "analyzer entry (the `-m` import chain must stay jax-free)")
    # (b) DYNAMIC: if jax appeared in sys.modules during this run —
    # everything above is pure ast over source text — the lint itself
    # has a spawn-purity-class bug.
    preloaded = _JAX_PRELOADED if jax_preloaded is None else jax_preloaded
    if chain_violations or ('jax' in sys.modules and not preloaded):
        for v in chain_violations:
            print(v.render(own_pkg_root), file=sys.stderr)
        print('vft-lint: FATAL: the analyzer process imported jax',
              file=sys.stderr)
        return EXIT_IMPURE
    return EXIT_FINDINGS if fresh else EXIT_CLEAN


if __name__ == '__main__':
    sys.exit(main())
