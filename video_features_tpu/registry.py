"""Extractor registry with lazy imports (reference main.py:20-38 dispatch)."""
from __future__ import annotations

import importlib
from typing import TYPE_CHECKING, Dict, Tuple

if TYPE_CHECKING:
    from video_features_tpu.config import Config
    from video_features_tpu.extract.base import BaseExtractor

# feature_type -> (module, class). Imports are deferred so a missing optional
# dependency for one family never breaks the others.
EXTRACTORS: Dict[str, Tuple[str, str]] = {
    'i3d': ('video_features_tpu.extract.i3d', 'ExtractI3D'),
    'r21d': ('video_features_tpu.extract.r21d', 'ExtractR21D'),
    's3d': ('video_features_tpu.extract.s3d', 'ExtractS3D'),
    'vggish': ('video_features_tpu.extract.vggish', 'ExtractVGGish'),
    'resnet': ('video_features_tpu.extract.resnet', 'ExtractResNet'),
    'raft': ('video_features_tpu.extract.raft', 'ExtractRAFT'),
    'clip': ('video_features_tpu.extract.clip', 'ExtractCLIP'),
    'timm': ('video_features_tpu.extract.timm', 'ExtractTIMM'),
}

# feature types whose extractor implements in-graph data parallelism
# (data_parallel=true). The single authoritative set — sanity_check
# consults it; deliberately an explicit literal (NOT frozenset(EXTRACTORS))
# so a future extractor without DP support trips the warn-and-disable path
# instead of silently claiming capability.
DATA_PARALLEL_FEATURES = frozenset(
    {'i3d', 'r21d', 's3d', 'vggish', 'resnet', 'raft', 'clip', 'timm'})

# feature types whose extractor implements the packed corpus mode
# (pack_across_videos=true — batch-major scheduling across videos,
# parallel/packing.py). Same deliberate-literal policy as above: a new
# extractor must opt in here AND set supports_packing, or sanity_check
# degrades the knob to the per-video loop with a warning.
PACKED_FEATURES = frozenset(
    {'i3d', 'r21d', 's3d', 'resnet', 'clip', 'timm'})

# feature types whose extractor accepts the bf16 fast lane
# (compute_dtype=bfloat16 — params cast bf16 at transplant, bf16
# activations with fp32 accumulation islands, ops/precision.py). Same
# deliberate-literal policy: a family joins ONLY once its rel-L2 error
# vs the float32 lane is measured and pinned (ops/precision.py
# BF16_REL_L2_BOUNDS, asserted by tests/test_precision.py) — an
# unmeasured family refuses the knob with a
# structured build-time error (ops/precision.check_compute_dtype)
# instead of shipping drift nobody bounded. i3d and raft stay OUT by
# measurement, not omission: the flow uint8-quantization cliff / 20-step
# GRU error compounding put them over the parity bar under bf16
# (ops/precision.BF16_REFUSALS names the numbers).
BF16_FEATURES = frozenset(
    {'r21d', 's3d', 'resnet', 'clip', 'timm', 'vggish'})

# feature types whose extractor accepts the int8 weight lane
# (compute_dtype=int8 — conv/linear weights quantized per-output-channel
# symmetric int8 at transplant time, dequantized in-graph at use, fp32
# activations; ops/quant.py). Same deliberate-literal policy as
# BF16_FEATURES: a family joins ONLY once its rel-L2 drift vs the fp32
# lane is measured and pinned (ops/precision.INT8_REL_L2_BOUNDS,
# asserted by tests/test_precision.py). The set is the bandwidth-bound
# framewise backbones the lane exists for; i3d/raft refuse by
# measurement (ops/precision.INT8_REFUSALS — the same error amplifiers
# that disqualify bf16), the video families (r21d/s3d/vggish) refuse by
# the generic no-measured-bound rule until someone pins them.
INT8_FEATURES = frozenset({'resnet', 'clip', 'timm'})

# feature types whose extractor can consume a LIVE session (ingress/):
# raw network frames windowed to the family's packed geometry
# (BaseExtractor.live_window_spec). Same deliberate-literal policy: a
# family must opt in here AND return a spec, or the ingress rejects the
# session up front with a clear error instead of failing mid-stream.
LIVE_FEATURES = frozenset(
    {'i3d', 'r21d', 's3d', 'resnet', 'clip', 'timm'})


def create_extractor(args: 'Config') -> 'BaseExtractor':
    feature_type = args['feature_type']
    try:
        module_name, class_name = EXTRACTORS[feature_type]
    except KeyError:
        raise NotImplementedError(f'Extractor {feature_type!r} is not implemented. '
                                  f'Known: {", ".join(EXTRACTORS)}')
    if hasattr(args, 'get'):
        from video_features_tpu.utils.device import enable_compilation_cache
        enable_compilation_cache(args.get('compilation_cache_dir'),
                                 str(args.get('device') or 'any'))
    module = importlib.import_module(module_name)
    extractor = getattr(module, class_name)(args)
    if hasattr(args, 'get'):
        # run fingerprint (config-aware resume) + content-addressed
        # feature cache; duck-typed arg objects without .get stay legacy
        extractor.configure_cache(args)
        # persistent executable store (aot/): programs load from disk
        # instead of compiling when a previous process published them.
        # Attach-only — warming is lazy (aot_call, at the ACTUAL batch
        # geometry) except on the serve boot path, which calls
        # aot_warm() after device placement.
        extractor.configure_aot(args)
        # flight recorder (obs/): trace_out / manifest_out knobs
        extractor.configure_obs(args)
        # decode farm (farm/): decode_workers / decode_farm_ring_mb
        extractor.configure_farm(args)
        # mesh-sharded packed execution (parallel/mesh.py): mesh_devices
        # resolves against this host's local devices at build time
        extractor.configure_mesh(args)
    return extractor
