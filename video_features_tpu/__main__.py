from video_features_tpu.cli import main

# the __name__ guard matters: decode-farm workers (farm/) are SPAWNED
# processes, and multiprocessing re-imports the parent's main module in
# the child — an unguarded SystemExit(main()) would re-run the whole CLI
# inside every decode worker
if __name__ == '__main__':
    raise SystemExit(main())
