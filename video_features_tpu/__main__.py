from video_features_tpu.cli import main

raise SystemExit(main())
