"""Functional NN building blocks (channels-last, XLA/TPU-native).

Every model in this framework is a pure function ``forward(params, x)`` over a
nested params pytree whose keys mirror the source torch ``state_dict`` names
(see video_features_tpu/transplant). Layouts are TPU-optimal channels-last:
images are NHWC, videos are NDHWC (D = time); conv kernels are stored
spatial-major with I/O last (HWIO / DHWIO) so XLA tiles them straight onto the
MXU without relayout.

Numerics parity notes (vs torch, for checkpoint-transplant fidelity):
  * conv: torch symmetric int padding → explicit (lo, hi) pairs here; TF-SAME
    asymmetric padding (I3D) is also expressible per-edge.
  * batch norm is inference-only: y = (x - mean) / sqrt(var + eps) * γ + β
    with running statistics — matches torch .eval() semantics.
  * max pool with ceil_mode / TF-SAME is built from explicit -inf padding.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array
IntOrTuple = Union[int, Sequence[int]]

# -- fp32 accumulation islands (the bf16 fast lane) --------------------------
#
# Under ``compute_dtype=bfloat16`` activations flow bf16 end to end, but
# a few ops accumulate MANY terms whose bf16 rounding compounds past the
# per-family parity bounds: normalization statistics (mean/var over
# thousands of elements), softmax (exp + sum), and pooling sums. Each such
# op below detects a bf16 input, computes in float32, and casts the result
# back — an explicit, local "island" rather than a global policy, so the
# float32 lane's graph is BYTE-IDENTICAL to the pre-lane programs (the
# branch is trace-time static on the abstract dtype; PROGRAMS.lock.json
# pins that). Matmuls/convs need no island: the MXU accumulates fp32
# internally for bf16 operands.


def _tuple(v: IntOrTuple, n: int) -> Tuple[int, ...]:
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    assert len(v) == n, f'expected {n} values, got {v}'
    return v


def _pad_pairs(padding: Union[IntOrTuple, Sequence[Tuple[int, int]], str], n: int):
    """Normalize padding to lax explicit (lo, hi) pairs, or pass 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if padding and isinstance(padding[0], (tuple, list)):
        return [tuple(p) for p in padding]
    return [(p, p) for p in padding]


def conv(x: Array, kernel: Array, stride: IntOrTuple = 1,
         padding: Union[IntOrTuple, Sequence[Tuple[int, int]], str] = 0,
         dilation: IntOrTuple = 1, groups: int = 1,
         bias: Optional[Array] = None) -> Array:
    """N-D convolution, channels-last. kernel: (*spatial, I/groups, O)."""
    n = kernel.ndim - 2
    spec = {1: ('NWC', 'WIO', 'NWC'),
            2: ('NHWC', 'HWIO', 'NHWC'),
            3: ('NDHWC', 'DHWIO', 'NDHWC')}[n]
    out = lax.conv_general_dilated(
        x, kernel.astype(x.dtype),
        window_strides=_tuple(stride, n),
        padding=_pad_pairs(padding, n),
        rhs_dilation=_tuple(dilation, n),
        dimension_numbers=spec,
        feature_group_count=groups,
    )
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def batch_norm(x: Array, p: Dict[str, Array], eps: float = 1e-5) -> Array:
    """Inference-mode batch norm over the trailing channel axis.

    ``p`` holds torch-named entries: weight (γ), bias (β), running_mean,
    running_var. Affine params may be absent (γ=1, β=0).
    """
    if x.dtype == jnp.bfloat16:
        # fp32 island: the rsqrt(var+eps) fold and the (x-mean)*inv
        # arithmetic run fp32, result cast back (BatchNorm statistics
        # island of the bf16 fast lane)
        return batch_norm(x.astype(jnp.float32), p, eps).astype(x.dtype)
    mean = p['running_mean'].astype(x.dtype)
    var = p['running_var'].astype(x.dtype)
    inv = lax.rsqrt(var + jnp.asarray(eps, x.dtype))
    out = (x - mean) * inv
    if 'weight' in p:
        out = out * p['weight'].astype(x.dtype)
    if 'bias' in p:
        out = out + p['bias'].astype(x.dtype)
    return out


def instance_norm(x: Array, p: Dict[str, Array], eps: float = 1e-5) -> Array:
    """InstanceNorm over spatial dims (channels-last), matching torch
    InstanceNorm2d (affine optional, no running stats — RAFT's fnet)."""
    if x.dtype == jnp.bfloat16:
        # fp32 island: per-sample statistics over whole spatial planes
        return instance_norm(x.astype(jnp.float32), p, eps).astype(x.dtype)
    axes = tuple(range(1, x.ndim - 1))
    mean = x.mean(axis=axes, keepdims=True)
    var = x.var(axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + jnp.asarray(eps, x.dtype))
    if 'weight' in p:
        out = out * p['weight'].astype(x.dtype)
    if 'bias' in p:
        out = out + p['bias'].astype(x.dtype)
    return out


def group_norm(x: Array, p: Dict[str, Array], num_groups: int,
               eps: float = 1e-5) -> Array:
    """GroupNorm (channels-last), matching torch nn.GroupNorm."""
    if x.dtype == jnp.bfloat16:
        # fp32 island: per-group statistics
        return group_norm(x.astype(jnp.float32), p, num_groups,
                          eps).astype(x.dtype)
    *lead, c = x.shape
    g = num_groups
    xg = x.reshape(*lead, g, c // g)
    axes = tuple(range(1, x.ndim - 1)) + (x.ndim,)
    mean = xg.mean(axis=axes, keepdims=True)
    var = xg.var(axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + jnp.asarray(eps, x.dtype))).reshape(x.shape)
    if 'weight' in p:
        out = out * p['weight'].astype(x.dtype)
    if 'bias' in p:
        out = out + p['bias'].astype(x.dtype)
    return out


def linear(x: Array, p: Dict[str, Array]) -> Array:
    """Dense layer; p['weight'] is stored transplanted as (I, O)."""
    out = x @ p['weight'].astype(x.dtype)
    if 'bias' in p:
        out = out + p['bias'].astype(x.dtype)
    return out


def relu(x: Array) -> Array:
    return jax.nn.relu(x)


def softmax(x: Array, axis: int = -1) -> Array:
    """softmax with the bf16 fast lane's fp32 island: exp + normalizing
    sum run fp32 for bf16 input (compounded rounding across wide
    attention rows is exactly what the per-family parity bounds can't
    absorb), result cast back; float32 input takes ``jax.nn.softmax``
    verbatim — the identical graph every call site lowered before."""
    if x.dtype == jnp.bfloat16:
        return jax.nn.softmax(x.astype(jnp.float32),
                              axis=axis).astype(x.dtype)
    return jax.nn.softmax(x, axis=axis)


def max_pool(x: Array, window: IntOrTuple, stride: Optional[IntOrTuple] = None,
             padding: Union[IntOrTuple, Sequence[Tuple[int, int]], str] = 0) -> Array:
    """Max pooling over the spatial dims of channels-last input."""
    n = x.ndim - 2
    window = _tuple(window, n)
    stride = window if stride is None else _tuple(stride, n)
    pads = _pad_pairs(padding, n)
    if not isinstance(pads, str):
        pads = [(0, 0)] + list(pads) + [(0, 0)]
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1,) + window + (1,),
        window_strides=(1,) + stride + (1,),
        padding=pads if not isinstance(pads, str) else pads,
    )


def avg_pool(x: Array, window: IntOrTuple, stride: Optional[IntOrTuple] = None,
             padding: Union[IntOrTuple, Sequence[Tuple[int, int]]] = 0,
             count_include_pad: bool = True) -> Array:
    """Average pooling matching torch AvgPool semantics."""
    if x.dtype == jnp.bfloat16:
        # fp32 island: window sums accumulate fp32 (also sidesteps the
        # float init_value / bf16 operand dtype mismatch in reduce_window)
        return avg_pool(x.astype(jnp.float32), window, stride, padding,
                        count_include_pad).astype(x.dtype)
    n = x.ndim - 2
    window = _tuple(window, n)
    stride = window if stride is None else _tuple(stride, n)
    pads = [(0, 0)] + list(_pad_pairs(padding, n)) + [(0, 0)]
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1,) + window + (1,),
        window_strides=(1,) + stride + (1,),
        padding=pads,
    )
    if count_include_pad:
        return summed / np.prod(window)
    ones = jnp.ones(x.shape[:-1] + (1,), x.dtype)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1,) + window + (1,),
        window_strides=(1,) + stride + (1,),
        padding=pads,
    )
    return summed / counts


def adaptive_avg_pool(x: Array, output_size: int = 1) -> Array:
    """AdaptiveAvgPool to (1,1,...) == global mean over spatial dims."""
    assert output_size == 1, 'only global pooling is used by these models'
    if x.dtype == jnp.bfloat16:
        # fp32 island: the global-pooling mean over thousands of
        # spatial positions is the single widest accumulation in the
        # conv families — and it feeds the feature output directly
        return x.astype(jnp.float32).mean(
            axis=tuple(range(1, x.ndim - 1))).astype(x.dtype)
    return x.mean(axis=tuple(range(1, x.ndim - 1)))


def same_padding_tf(in_size: int, kernel: int, stride: int,
                    dilation: int = 1) -> Tuple[int, int]:
    """TF-SAME per-edge (lo, hi) padding — asymmetric, extra on the high side.

    This is the semantics I3D inherited from its TF origin (reference
    models/i3d/i3d_src/i3d_net.py:8-34 emulates it in torch with ConstantPad3d;
    here it is just explicit lax padding).
    """
    eff_k = (kernel - 1) * dilation + 1
    out = -(-in_size // stride)  # ceil
    pad = max(0, (out - 1) * stride + eff_k - in_size)
    return pad // 2, pad - pad // 2


def ceil_mode_padding(in_size: int, kernel: int, stride: int) -> Tuple[int, int]:
    """Torch ceil_mode pooling → (0, extra) high-side padding."""
    out_ceil = -(-(in_size - kernel) // stride) + 1
    needed = (out_ceil - 1) * stride + kernel - in_size
    return 0, max(0, needed)
