"""Host-side (PIL/NumPy) frame transforms — deliberately jax-free.

These are the preprocessing primitives that run on decode threads and in
the decode-farm worker PROCESSES (``farm/``): a farm worker imports this
module (plus cv2/PIL) and nothing else, so spawning a worker never pays
the jax/XLA import or risks initializing a backend in a child process.
``ops.transforms`` re-exports everything here, so existing device-side
import sites are unchanged.

Numerics: exact parity with the reference's PIL-based ``ResizeImproved``
and torchvision's ``CenterCrop`` — see the per-function notes.

Dtype contract: **uint8 in, uint8 out.** Frames stay integer until they
are on the device; every float conversion (and its precision) belongs to
the jitted step, where PROGRAMS.lock.json pins it (the no-f64 rule).
A host transform drifting to numpy's default float64 — easy to do
silently with ``/ 255.0``-style math — would make decode-farm workers
and in-process decode disagree the moment jax's implicit downcast
stopped hiding it; :func:`frames_match_device_contract` is the
assertion both paths (and the parity tests) hold against.
"""
from __future__ import annotations

import numpy as np


def frames_match_device_contract(frame: np.ndarray) -> bool:
    """True iff ``frame`` honors the host-side dtype contract (uint8 —
    the only dtype the packed H2D path ships for video frames). Farm
    workers and the in-process windower both feed batches that must
    agree byte-for-byte; a float-dtype frame here means a transform
    leaked numpy default-dtype math."""
    return frame.dtype == np.uint8


def pil_edge_resize_geometry(h: int, w: int, size: int,
                             to_smaller_edge: bool = True):
    """(oh, ow) of a PIL edge resize, or None when it no-ops — the ONE
    home of the edge-selection + ``int(size * other/edge)`` truncation
    arithmetic (reference ResizeImproved, models/transforms.py:191-242),
    shared by :func:`resize_pil` and the device-resize path
    (extract/i3d.py)."""
    if (w <= h and w == size) or (h <= w and h == size):
        return None
    if (w < h) == to_smaller_edge:
        return int(size * h / w), size
    return size, int(size * w / h)


def resize_pil(frame: np.ndarray, size: int,
               to_smaller_edge: bool = True,
               interpolation: str = 'bilinear') -> np.ndarray:
    """Host-side PIL edge resize, aspect preserved.

    Exact parity with the reference's PIL-based `ResizeImproved`
    (reference models/transforms.py:191-242): no-op when the matched edge
    already equals ``size``; the scaled side uses ``int(size * other/edge)``
    (truncation, PIL convention). ``interpolation='bicubic'`` gives the
    torchvision Resize(BICUBIC) used by CLIP (reference clip_src/clip.py
    transform).
    """
    from PIL import Image

    modes = {'bilinear': Image.BILINEAR, 'bicubic': Image.BICUBIC}
    h, w = frame.shape[:2]
    geom = pil_edge_resize_geometry(h, w, size, to_smaller_edge)
    if geom is None:
        return frame
    oh, ow = geom
    img = Image.fromarray(frame)
    return np.asarray(img.resize((ow, oh), modes[interpolation]))


def short_side_resize_pil(frame: np.ndarray, size: int) -> np.ndarray:
    """min(H, W) → ``size`` via PIL bilinear (see :func:`resize_pil`)."""
    return resize_pil(frame, size, to_smaller_edge=True)


def center_crop_host(frame: np.ndarray, size: int) -> np.ndarray:
    """Host-side HWC center crop with torchvision's round-to-even offsets
    (the reference's CenterCrop behavior across all frame-wise extractors)."""
    h, w = frame.shape[:2]
    i = int(round((h - size) / 2.0))
    j = int(round((w - size) / 2.0))
    return frame[i:i + size, j:j + size]
