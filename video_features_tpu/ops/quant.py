"""Post-training int8 weight quantization (the ``compute_dtype=int8`` lane).

The precision ladder's bottom rung: conv/linear weights are quantized ONCE
at transplant time — per-output-channel symmetric int8, the standard
post-training weight-only scheme — and dequantized IN-GRAPH at use, so

  * params are int8 in HBM from the first ``device_put`` (a quarter of the
    fp32 residency and H2D bytes; the byte-ranked serve ``DevicePlacer``
    stacks ~4x the warm entries per chip),
  * activations stay in the fp32 compute path (``compute_jnp_dtype`` is
    float32 for this lane — the dequant emits one convert+multiply per
    weight, then the math is the float32 graph), and
  * the float32/bf16 lanes are untouched: :func:`dequantize_tree` is a
    structural identity on trees with no :class:`QuantizedTensor` in them,
    so their StableHLO stays byte-identical (PROGRAMS.lock.json pins it).

Layout contract: quantization runs AFTER the transplant re-layout
(torch2jax), where the output channel is always the LAST axis — conv
(*spatial, I, O), linear (I, O) — so the per-channel ``scale`` is a flat
``(O,)`` float32 vector broadcasting over the last axis in both the
quantizer and the in-graph dequant. Eligibility mirrors the transplant's
own re-layout rule (``convert_tensor``): '.weight' tensors of ndim >= 2,
minus the ``no_transpose`` embedding tables; biases, norm scales/stats and
every other 1-D param stay float32 — the lane's DECLARED fp32 minority,
which the vft-programs ``int8-census`` rule bounds (fp32 bytes < int8
bytes per program).

Scales are weight-derived and deterministic (amax/127 per channel), so a
rebuild from the same checkpoint always lands the same int8 bytes. The
calibration tool (``tools/calibrate_int8.py``) additionally PINS the
per-tensor scale table into a checkpoint-adjacent ``.int8-scales.npz``
(:func:`scale_table_path`) and measures the family's feature rel-L2 drift
— a pinned table is consumed verbatim at build (:func:`load_scale_table`),
making the quantization reproducible even across checkpoint re-exports
that perturb weight bytes.
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import jax
import numpy as np

# symmetric int8: the scale maps amax -> 127 and values clip to +/-127
# (never -128 — symmetry keeps the dequant a single multiply, no zero
# point anywhere in the graph)
QMAX = 127


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """An int8-quantized weight: ``q`` (int8, transplanted layout) and the
    per-output-channel ``scale`` (float32, broadcast shape — ``O`` on the
    channel axis, 1 elsewhere). Registered as a pytree NODE so the whole
    params machinery
    (device_put, jit flattening, ``params_nbytes``, the vft-programs
    parameter census, abstract ShapeDtypeStruct mapping) sees exactly two
    leaves — the int8 payload and the fp32 scale — with no special cases.

    Deliberately NOT array-duck-typed: models access weights as raw
    arrays (``x @ p['weight']``, ``lax.conv_general_dilated``), and a
    half-faithful wrapper would fail deep inside XLA instead of at the
    seam. The one legal consumer is :func:`dequantize_tree` at the top of
    an accepting family's forward — anything else touching a quantized
    leaf raises immediately.
    """

    __slots__ = ('q', 'scale')

    def __init__(self, q, scale) -> None:
        self.q = q
        self.scale = scale

    def dequantize(self, dtype=None):
        """``q * scale`` in ``dtype`` (float32 default) — the in-graph
        use-site expansion: one convert + one broadcast multiply per
        weight, then the downstream math is the ordinary float graph."""
        import jax.numpy as jnp
        dtype = dtype or jnp.float32
        return jnp.asarray(self.q).astype(dtype) * jnp.asarray(
            self.scale).astype(dtype)

    @property
    def shape(self):
        return self.q.shape

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        # no validation: unflatten must accept abstract leaves
        # (ShapeDtypeStruct / tracers) for AOT lowering and tree_map
        del aux
        return cls(*children)

    def __repr__(self) -> str:
        return (f'QuantizedTensor(q={getattr(self.q, "shape", self.q)}, '
                f'scale={getattr(self.scale, "shape", self.scale)})')


def _derive_scale(a: np.ndarray, axis: int) -> np.ndarray:
    """amax/127 per channel along ``axis``, in BROADCAST shape (1s on
    every other axis) so the dequant is a plain multiply whatever the
    channel axis is. All-zero channels get scale 1.0 (their int8 payload
    is all zeros either way — the guard only keeps the dequant multiply
    finite)."""
    amax = np.max(np.abs(a), axis=tuple(
        ax for ax in range(a.ndim) if ax != axis % a.ndim), keepdims=True)
    scale = (amax / float(QMAX)).astype(np.float32)
    return np.where(scale > 0, scale, np.float32(1.0)).astype(np.float32)


def quantize_array(arr: np.ndarray,
                   scale: Optional[np.ndarray] = None,
                   axis: int = -1) -> QuantizedTensor:
    """Per-output-channel symmetric int8 quantization of one transplanted
    weight. ``axis`` is the output-channel axis — LAST for everything the
    transplant re-laid-out (conv (*spatial, I, O), linear (I, O)), axis 0
    for CLIP's torch-layout ``in_proj_weight`` (3E, E). ``scale``
    overrides the derived amax/127 per-channel scales — the
    calibration-table consumption path; any shape broadcastable against
    ``arr`` with ``O`` channel entries."""
    a = np.asarray(arr, dtype=np.float32)
    if a.ndim < 2:
        raise ValueError(f'per-channel quantization needs ndim >= 2; '
                         f'got shape {a.shape}')
    if scale is None:
        scale = _derive_scale(a, axis)
    else:
        scale = np.asarray(scale, dtype=np.float32)
        if scale.ndim != a.ndim:     # flat (O,) table entry → broadcast shape
            shape = [1] * a.ndim
            shape[axis % a.ndim] = scale.size
            scale = scale.reshape(shape)
        scale = np.where(scale > 0, scale,
                         np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(a / scale), -QMAX, QMAX).astype(np.int8)
    return QuantizedTensor(q, scale)


def _channel_axis(name: str, arr: Any,
                  skip: Optional[set]) -> Optional[int]:
    """Output-channel axis for one flat (dot-named, transplanted-layout)
    entry, or None when it must stay float32. Mirrors the transplant
    re-layout rule (torch2jax.convert_tensor): '.weight' tensors of
    ndim >= 2 had their output channel moved LAST (axis -1) — minus the
    ``no_transpose`` embedding/gather tables, which keep torch layout
    and stay float32; embedding tables are ALSO excluded by name
    ('...embedding.weight') because pre-transplanted .npz archives no
    longer carry the conversion-time no_transpose set, and a gather
    table has no output-channel axis to quantize along. CLIP's fused
    attention ``in_proj_weight`` (torch layout (3E, E), transposed at
    use) quantizes along axis 0."""
    if skip and name in skip:
        return None
    arr = np.asarray(arr)
    if arr.ndim < 2 or not np.issubdtype(arr.dtype, np.floating):
        return None
    if name.endswith('in_proj_weight'):
        return 0
    if not (name.endswith('.weight') or name == 'weight'):
        return None
    parts = name.split('.')
    if len(parts) >= 2 and 'embedding' in parts[-2]:
        return None
    return -1


def quantize_flat(flat: Mapping[str, np.ndarray], *,
                  skip: Optional[set] = None,
                  scales: Optional[Mapping[str, np.ndarray]] = None,
                  ) -> Dict[str, Any]:
    """int8-quantize every eligible weight of a FLAT (dot-named,
    transplanted-layout) param dict; everything else is cast to float32 —
    the lane's declared fp32 minority (biases, norm params, the scales
    themselves). ``scales`` is a pinned per-tensor scale table
    (:func:`load_scale_table`); absent entries fall back to the derived
    weight amax scales, which are bit-identical for the same weight
    bytes."""
    out: Dict[str, Any] = {}
    for name, arr in flat.items():
        axis = _channel_axis(name, arr, skip)
        if axis is not None:
            out[name] = quantize_array(
                arr, scale=scales.get(name) if scales else None,
                axis=axis)
        elif np.issubdtype(np.asarray(arr).dtype, np.floating):
            out[name] = np.asarray(arr, dtype=np.float32)
        else:
            out[name] = arr
    return out


def dequantize_tree(params: Any, dtype=None) -> Any:
    """Expand every :class:`QuantizedTensor` in ``params`` to its float
    array (float32 default); a STRUCTURAL IDENTITY — same leaves, zero
    graph ops — on trees that carry none, which is what keeps the
    float32/bf16 lanes' StableHLO byte-identical with the call compiled
    into every accepting family's forward."""
    return jax.tree_util.tree_map(
        lambda leaf: (leaf.dequantize(dtype)
                      if isinstance(leaf, QuantizedTensor) else leaf),
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def tree_is_quantized(params: Any) -> bool:
    """True when any leaf of ``params`` is a :class:`QuantizedTensor`."""
    found = False
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            found = True
            break
    return found


# -- the checkpoint-adjacent scale table -------------------------------------

def scale_table_path(checkpoint_path: str) -> str:
    """The ONE naming convention for a checkpoint's pinned int8 scale
    table: ``<ckpt>.int8-scales.npz`` right next to the checkpoint, so
    the table travels with the weights it calibrates and a build resolves
    it with no extra config knob."""
    return f'{checkpoint_path}.int8-scales.npz'


def save_scale_table(path: str, scales: Mapping[str, np.ndarray],
                     meta: Optional[Mapping[str, str]] = None) -> None:
    """Write a per-tensor scale table (flat dot-named keys -> float32
    ``(O,)`` vectors). ``meta`` string entries ride along under
    ``__meta_<key>`` (the calibration tool records the measured rel-L2
    and the corpus it measured on)."""
    payload = {k: np.asarray(v, np.float32) for k, v in scales.items()}
    for k, v in (meta or {}).items():
        payload[f'__meta_{k}'] = np.asarray(str(v))
    np.savez(path, **payload)


def load_scale_table(path: str) -> Dict[str, np.ndarray]:
    """Read a :func:`save_scale_table` table back (meta entries dropped);
    ``{}`` when the file does not exist — absent table means derived
    scales, never an error."""
    import os
    if not os.path.exists(path):
        return {}
    with np.load(path) as data:
        return {k: data[k] for k in data.files
                if not k.startswith('__meta_')}


def derive_scales(flat: Mapping[str, np.ndarray], *,
                  skip: Optional[set] = None) -> Dict[str, np.ndarray]:
    """The derived per-channel scales for every eligible weight of a flat
    transplanted dict — what :func:`quantize_flat` would use; the
    calibration tool pins exactly these into the table."""
    out: Dict[str, np.ndarray] = {}
    for name, arr in flat.items():
        axis = _channel_axis(name, arr, skip)
        if axis is not None:
            out[name] = _derive_scale(np.asarray(arr, np.float32), axis)
    return out
