"""Pallas TPU kernels for RAFT's correlation-pyramid window lookup.

Two kernels live here. The lane-packed :func:`lookup_corr_lanes` (bottom of
file) is the production TPU default (auto-dispatched by
models/raft.py::_resolve_auto_lookup; 14.3 → 26.9 clips/s/chip on the fused
I3D bench on v5e). The window-slice :func:`lookup_corr` below is the
``VFT_RAFT_LOOKUP=pallas`` alternate formulation of the same op; off-TPU the
dense-matmul lookup_corr_dense in models/raft.py is used instead.

The reference implements the lookup (reference models/raft/raft_src/corr.py:29-50)
as 81 independent bilinear samples per pixel per pyramid level — a gather of
``N·(2r+1)²·4corners·levels`` scattered elements from HBM on every one of the
20 GRU iterations. Gathers are the one access pattern TPUs do poorly; this
kernel removes them entirely using two structural facts:

1. The window offsets are **integers** (``d ∈ {-r..r}``), so the fractional
   part of every sample coordinate in a window is the same — all 81 samples
   share ONE pair of bilinear weights ``(wy, wx)``. The whole window is a
   single integer-aligned ``(2r+2)×(2r+2)`` patch read plus a 4-term blend
   of its shifted ``(2r+1)×(2r+1)`` views.
2. ``grid_sample(padding_mode='zeros')`` semantics can be *pre-baked* by
   zero-padding each pyramid level once, outside the 20-iteration scan, so
   the patch read needs no bounds masking inside the kernel.

Each pyramid level is padded by ``PAD = 2r+3`` and stored **transposed**
``(N, wp, hp)`` so the kernel can emit the reference's dy-major output
ordering (see models/raft.py lookup_corr — the reference adds ``(dy, dx)``
deltas onto ``(x, y)`` centroids, corr.py:38-44) without an in-kernel
transpose. Per pixel the kernel does one dynamic-slice VMEM read and four
fused multiply-adds over a 9×9 tile; per-pixel scalars (patch origin and
bilinear weights) arrive through SMEM blocks.

CPU tests run the same kernel under ``interpret=True``.

Numerics: the kernel is exact in ordering and padding semantics vs the XLA
gather path; per-element differences are fp-reorder noise (~1e-6 on real
corr magnitudes). Under RAFT's trained (contracting) update dynamics that
stays within the 2e-3 torch-parity tolerance; with random weights the
iteration is non-contracting and amplifies ulp noise, so cross-path tests
compare at few iterations only.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_N = 32


def _pad_block(n: int) -> int:
    return -n % BLOCK_N


def prep_pyramid(pyramid: Sequence[jax.Array], radius: int) -> List[jax.Array]:
    """Zero-pad + transpose each level once, outside the GRU scan.

    pyramid levels: (N, h, w, 1) → (N', w + 2·PAD, h + 2·PAD), padded with
    zeros (matching the reference's zeros padding_mode) and transposed so the
    kernel reads dy-major windows contiguously. N is also rounded up to a
    BLOCK_N multiple here — once, outside the 20-iteration GRU scan — so the
    per-iteration lookup never copies the pyramid.
    """
    pad = 2 * radius + 3
    out = []
    for corr in pyramid:
        c = jnp.squeeze(corr, -1)
        c = jnp.pad(c, [(0, _pad_block(c.shape[0])), (pad, pad), (pad, pad)])
        out.append(jnp.swapaxes(c, 1, 2))
    return out


def _level_kernel(p1: int):
    """Kernel over one pyramid level; p1 = 2r+1 (window side)."""
    p2 = p1 + 1

    def kernel(xs_ref, ys_ref, wx_ref, wy_ref, corr_ref, out_ref):
        hp = corr_ref.shape[2]

        def body(k, _):
            xs = xs_ref[k, 0]
            ys = ys_ref[k, 0]
            wx = wx_ref[k, 0]
            wy = wy_ref[k, 0]
            # corr is transposed: leading spatial dim is x, trailing is y.
            # Mosaic allows a dynamic-start slice on the sublane dim (xs) but
            # the lane dim demands 128-aligned starts — so read the full lane
            # extent and select the p2 columns at dynamic ys with a one-hot
            # matmul (iota-compare builds the selector; the MXU does the
            # "slice").
            rows = corr_ref[k, pl.ds(xs, p2), :]                  # (p2, hp)
            col = jax.lax.broadcasted_iota(jnp.int32, (hp, p2), 0)
            j = jax.lax.broadcasted_iota(jnp.int32, (hp, p2), 1)
            sel = (col == ys + j).astype(rows.dtype)              # (hp, p2)
            patch = jax.lax.dot_general(
                rows, sel, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)               # (p2, p2)
            out_ref[k, :, :] = (
                (1 - wx) * (1 - wy) * patch[0:p1, 0:p1]
                + wx * (1 - wy) * patch[1:p2, 0:p1]
                + (1 - wx) * wy * patch[0:p1, 1:p2]
                + wx * wy * patch[1:p2, 1:p2]
            )
            return 0

        jax.lax.fori_loop(0, out_ref.shape[0], body, 0)

    return kernel


def _lookup_level(corr_t: jax.Array, coords: jax.Array, radius: int,
                  interpret: bool) -> jax.Array:
    """One prepped level (N', wp, hp) + (N, 2) coords → (N, (2r+1)²).

    N' is the BLOCK_N-rounded row count from :func:`prep_pyramid`; only the
    per-call scalars are padded here. Output element ``i·(2r+1)+j`` is the
    sample at ``(x + d[i], y + d[j])`` — the reference's dy-major ordering.
    """
    n = coords.shape[0]
    n_pad, wp, hp = corr_t.shape
    assert n_pad == n + _pad_block(n), (n_pad, n)
    pad = 2 * radius + 3
    w, h = wp - 2 * pad, hp - 2 * pad
    p1 = 2 * radius + 1

    # Clamp so every window lands inside the zero-padded array. Anything
    # clamped was ≥ 1px outside the map on every sample → exactly 0 under
    # zeros padding, which the pad region reproduces.
    x = jnp.clip(coords[:, 0], -radius - 2.0, w + radius + 1.0)
    y = jnp.clip(coords[:, 1], -radius - 2.0, h + radius + 1.0)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    xs = (x0.astype(jnp.int32) - radius + pad)[:, None]
    ys = (y0.astype(jnp.int32) - radius + pad)[:, None]
    wx = (x - x0).astype(corr_t.dtype)[:, None]
    wy = (y - y0).astype(corr_t.dtype)[:, None]

    extra = _pad_block(n)
    if extra:
        xs, ys = (jnp.pad(a, [(0, extra), (0, 0)]) for a in (xs, ys))
        wx, wy = (jnp.pad(a, [(0, extra), (0, 0)]) for a in (wx, wy))

    scalar_spec = pl.BlockSpec((BLOCK_N, 1), lambda i: (i, 0),
                               memory_space=pltpu.SMEM)
    out = pl.pallas_call(
        _level_kernel(p1),
        grid=(n_pad // BLOCK_N,),
        in_specs=[scalar_spec, scalar_spec, scalar_spec, scalar_spec,
                  pl.BlockSpec((BLOCK_N, wp, hp), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((BLOCK_N, p1, p1), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, p1, p1), corr_t.dtype),
        interpret=interpret,
    )(xs, ys, wx, wy, corr_t)
    return out[:n].reshape(n, p1 * p1)


def lookup_corr(prepped: Sequence[jax.Array], coords: jax.Array,
                radius: int = 4, interpret: bool = False) -> jax.Array:
    """Sample (2r+1)² windows at every level of a prepped pyramid.

    prepped: output of :func:`prep_pyramid`; coords: (B, H, W, 2) level-0
    (x, y) pixel positions. Returns (B, H, W, levels·(2r+1)²), bit-identical
    in ordering and padding semantics to the XLA gather path
    (models/raft.py lookup_corr).
    """
    b, hh, ww, _ = coords.shape
    flat = coords.reshape(b * hh * ww, 2)
    out = [_lookup_level(corr_t, flat / (2.0 ** i), radius, interpret)
           for i, corr_t in enumerate(prepped)]
    return jnp.concatenate(out, axis=-1).reshape(b, hh, ww, -1)


# ---------------------------------------------------------------------------
# Lane-packed variant: 128 pixels per lane tile, mask-reduce window sums.
#
# The window-slice kernel above iterates pixels serially; this one packs 128
# pixels into the lane dimension and extracts windows with iota-compare
# masks + reductions — pure VPU work with no dynamic slicing at all, so it
# both satisfies Mosaic's layout rules and vectorizes fully. Out-of-range
# window indices simply never match the iota, which reproduces the
# reference's zeros padding_mode without any pre-padding.

LANES = 128


def prep_pyramid_lanes(pyramid: Sequence[jax.Array]) -> List[jax.Array]:
    """(N, h, w, 1) levels → (h, w, N') with N' padded to a LANES multiple."""
    out = []
    for corr in pyramid:
        c = jnp.squeeze(corr, -1)                        # (N, h, w)
        pad = -c.shape[0] % LANES
        c = jnp.pad(c, [(0, pad), (0, 0), (0, 0)])
        out.append(c.transpose(1, 2, 0))                 # (h, w, N')
    return out


def prep_pyramid_lanes_fused(fmap1: jax.Array, fmap2: jax.Array,
                             levels: int = 4) -> List[jax.Array]:
    """Feature maps → lane-layout pyramid DIRECTLY, no (N, h, w) detour
    and no giant-volume pooling.

    Two compounding reformulations over ``build_corr_pyramid`` +
    :func:`prep_pyramid_lanes` (which materialized the ~2 GB level-0
    volume in (N, h, w) layout, physically transposed it to the kernel's
    (h, w, N') layout, then average-pooled the volume three times — the
    worst HBM pattern in the fused step, 106.8 ms of the 362 ms fixed
    phase at batch-16 CLI geometry vs a ~10-20 ms traffic floor):

    The einsum emits straight into (h, w, b·n) lane order and the
    levels pool over the LEADING axes (lane dim stays minor, sequential
    HBM traffic): 106.8 → 74.8 ms isolated, headline 9.44 → 9.69
    clips/s. Same valid 2×2/stride-2 window set as ``avg_pool`` (odd
    trailing row/col dropped); numerics at 1e-9-class reassociation
    noise vs the two-step path, pinned by tests/test_pallas_corr.py.

    Tried and rejected: pooling commutes with the dot product, so each
    level can be computed as ⟨f1, avgpool^L(fmap2)⟩ with no giant-volume
    pooling at all — 74.8 → 32.1 ms ISOLATED, but 9.69 → 9.53 clips/s
    in the fused step (consistent across runs): re-reading the ~360 MB
    f1 operand for four einsums costs the composed graph more than the
    volume pooling it saves. End-to-end wins; the isolated number lies.
    """
    B, H, W, D = fmap1.shape
    f1 = fmap1.reshape(B, H * W, D)
    corr_t = jnp.einsum('bnd,bhwd->hwbn', f1, fmap2) / jnp.sqrt(
        jnp.asarray(D, fmap1.dtype))
    corr_t = corr_t.reshape(H, W, B * H * W)
    pad = -corr_t.shape[-1] % LANES
    corr_t = jnp.pad(corr_t, [(0, 0), (0, 0), (0, pad)])
    out = [corr_t]
    for _ in range(levels - 1):
        h, w, n = corr_t.shape
        h2, w2 = h // 2, w // 2
        corr_t = corr_t[:h2 * 2, :w2 * 2].reshape(h2, 2, w2, 2, n).mean((1, 3))
        out.append(corr_t)
    return out


def _lanes_kernel(p1: int, h: int, w: int):
    """Kernel over one level, one 128-pixel lane tile; p1 = 2r+1."""
    p2 = p1 + 1
    r = (p1 - 1) // 2

    def kernel(xi_ref, yi_ref, fx_ref, fy_ref, corr_ref, out_ref):
        corr = corr_ref[...]                              # (h, w, LANES)
        fx = fx_ref[0, :]                                 # (LANES,)
        fy = fy_ref[0, :]
        xi = xi_ref[0, :]
        yi = yi_ref[0, :]
        iota_w = jax.lax.broadcasted_iota(jnp.int32, (w, LANES), 0)
        iota_h = jax.lax.broadcasted_iota(jnp.int32, (h, LANES), 0)

        # x pass: S_k[h, n] = Σ_w corr[h, w, n] · [w == xi_n + (k - r)]
        s = []
        for k in range(p2):
            mask = (iota_w == (xi[None, :] + (k - r))).astype(corr.dtype)
            s.append(jnp.sum(corr * mask[None, :, :], axis=1))   # (h, LANES)
        # bilinear x blend: consecutive sums share the shifted index
        rows = [(1 - fx)[None, :] * s[i] + fx[None, :] * s[i + 1]
                for i in range(p1)]                              # 9 × (h, LANES)

        # y pass: the k-masks are row-independent, so compute them once and
        # contract every row against them; single stacked store at the end
        # (81 scattered single-sublane stores compile poorly)
        masks_h = [(iota_h == (yi[None, :] + (k - r))).astype(corr.dtype)
                   for k in range(p2)]
        outs = []
        for i in range(p1):
            v = [jnp.sum(rows[i] * masks_h[k], axis=0) for k in range(p2)]
            outs.extend((1 - fy) * v[j] + fy * v[j + 1] for j in range(p1))
        out_ref[...] = jnp.stack(outs, axis=0)                   # (81, LANES)

    return kernel


def _lookup_level_lanes(corr_t: jax.Array, coords: jax.Array, radius: int,
                        interpret: bool) -> jax.Array:
    """One (h, w, N') level + (N, 2) coords → (N, (2r+1)²)."""
    n = coords.shape[0]
    h, w, n_pad = corr_t.shape
    p1 = 2 * radius + 1

    x = coords[:, 0]
    y = coords[:, 1]
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    xi = x0.astype(jnp.int32)[None, :]                   # window base (x)
    yi = y0.astype(jnp.int32)[None, :]
    fx = (x - x0).astype(corr_t.dtype)[None, :]
    fy = (y - y0).astype(corr_t.dtype)[None, :]

    extra = n_pad - n
    if extra:
        xi, yi, fx, fy = (jnp.pad(a, [(0, 0), (0, extra)])
                          for a in (xi, yi, fx, fy))

    vec_spec = pl.BlockSpec((1, LANES), lambda t: (0, t),
                            memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _lanes_kernel(p1, h, w),
        grid=(n_pad // LANES,),
        in_specs=[vec_spec, vec_spec, vec_spec, vec_spec,
                  pl.BlockSpec((h, w, LANES), lambda t: (0, 0, t),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((p1 * p1, LANES), lambda t: (0, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((p1 * p1, n_pad), corr_t.dtype),
        interpret=interpret,
    )(xi, yi, fx, fy, corr_t)
    return out[:, :n].T                                  # (N, 81)


def lookup_corr_lanes(prepped: Sequence[jax.Array], coords: jax.Array,
                      radius: int = 4, interpret: bool = False) -> jax.Array:
    """Lane-packed lookup over a :func:`prep_pyramid_lanes` pyramid.

    Same output as models/raft.py lookup_corr (dy-major ordering, zeros
    padding): element ``i·(2r+1)+j`` samples ``(x + d[i], y + d[j])``.
    """
    b, hh, ww, _ = coords.shape
    flat = coords.reshape(b * hh * ww, 2)
    out = [_lookup_level_lanes(corr_t, flat / (2.0 ** i), radius, interpret)
           for i, corr_t in enumerate(prepped)]
    return jnp.concatenate(out, axis=-1).reshape(b, hh, ww, -1)
