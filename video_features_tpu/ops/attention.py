"""Attention ops: dense, blockwise (flash-style), and ring sequence-parallel.

The reference's models are convolutional or clip-local, so it has no
long-sequence machinery at all (SURVEY.md §2.3, §5.7) — long videos are
handled by sliding windows. This framework treats long-context as
first-class: token sequences too large for one device's HBM (e.g. every
frame's ViT tokens of a long video treated as one temporal sequence) are
sharded over a mesh axis and attended with **ring attention** — KV shards
rotate around the ring via ``lax.ppermute`` (ICI neighbor exchange, no
all-gather) while each device accumulates its queries' online softmax.

All three paths compute bit-comparable results (same online-softmax math,
f32 accumulation):

  * :func:`dense_attention` — one fused XLA softmax(QKᵀ)V; the baseline.
  * :func:`blockwise_attention` — ``lax.scan`` over KV chunks with running
    (max, denom, out) — O(S·block) memory instead of O(S²), single device.
  * :func:`ring_attention` — blockwise over the mesh axis; memory AND
    compute sharded. Use under ``shard_map`` with the sequence axis split.

Shapes follow (B, S, H, D) [batch, sequence, heads, head_dim].
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _scale(q: jax.Array, scale: Optional[float]) -> float:
    return scale if scale is not None else q.shape[-1] ** -0.5


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    scale: Optional[float] = None) -> jax.Array:
    """softmax(QKᵀ·scale)V over (B, S, H, D) tensors."""
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) * _scale(q, scale)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)


def _online_block(q, m, l, o, kb, vb, scale, valid=None):
    """One online-softmax accumulation step against KV block (kb, vb).

    ``valid`` (block_size,) bool masks padded keys out of the softmax
    (scores → -inf ⇒ p → 0); fully-padded blocks leave the carry unchanged
    because m_new falls back to the running max.
    """
    s = jnp.einsum('bqhd,bkhd->bqhk', q, kb).astype(jnp.float32) * scale
    if valid is not None:
        s = jnp.where(valid, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    # m_new stays -inf until the first unmasked key (a fully-padded shard
    # can be processed first under ring sharding); exponentiate against a
    # finite stand-in so exp(-inf - -inf) never makes a NaN — p and alpha
    # are then exactly 0 and the carry passes through unchanged.
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe)
    alpha = jnp.exp(m - m_safe)
    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
    o_new = o * alpha + jnp.einsum('bqhk,bkhd->bqhd', p,
                                   vb.astype(jnp.float32))
    return m_new, l_new, o_new


def _online_init(q):
    b, sq, h, d = q.shape
    m = jnp.full((b, sq, h, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, sq, h, 1), jnp.float32)
    o = jnp.zeros((b, sq, h, d), jnp.float32)
    return m, l, o


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_size: int = 512,
                        scale: Optional[float] = None) -> jax.Array:
    """Memory-efficient attention: scan over KV blocks, O(S·block) memory.

    Ragged S is handled by zero-padding KV to a block multiple and masking
    the padded keys out of the online softmax — a ViT token count
    (grid² + 1 cls) is never block-aligned, and this is the production path
    for high-resolution inputs past BLOCKWISE_THRESHOLD tokens.
    """
    b, sk, h, d = k.shape
    block_size = min(block_size, sk)
    pad = (-sk) % block_size
    sc = _scale(q, scale)
    valid = None
    if pad:
        k = jnp.pad(k, [(0, 0), (0, pad), (0, 0), (0, 0)])
        v = jnp.pad(v, [(0, 0), (0, pad), (0, 0), (0, 0)])
        valid = (jnp.arange(sk + pad) < sk).reshape(-1, block_size)
    n_blocks = (sk + pad) // block_size
    kb = k.reshape(b, n_blocks, block_size, h, d).swapaxes(0, 1)
    vb = v.reshape(b, n_blocks, block_size, h, d).swapaxes(0, 1)

    def step(carry, blk):
        if valid is None:
            kv_k, kv_v = blk
            mask = None
        else:
            kv_k, kv_v, mask = blk
        m, l, o = _online_block(q, *carry, kv_k, kv_v, sc, valid=mask)
        return (m, l, o), None

    xs = (kb, vb) if valid is None else (kb, vb, valid)
    (m, l, o), _ = lax.scan(step, _online_init(q), xs)
    return (o / l).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str,
                   scale: Optional[float] = None,
                   kv_valid: Optional[jax.Array] = None) -> jax.Array:
    """Sequence-parallel attention over a mesh axis (call under shard_map).

    Each device holds one (B, S/n, H, D) shard of q, k, v. KV shards rotate
    one ring hop per step (``lax.ppermute`` — neighbor traffic over ICI);
    after n steps every query has attended every key. Online softmax makes
    the accumulation order-invariant, so results match dense attention on
    the unsharded sequence to fp tolerance.

    ``kv_valid`` (S/n,) bool masks this device's PADDED key positions out
    of every query's softmax (it rotates around the ring with its KV
    shard) — how ragged token counts (e.g. a ViT's grid²+1) shard over a
    mesh axis that does not divide them. Rows of fully-masked q padding
    produce garbage (denominator from real keys only) — slice them off
    after gathering.
    """
    n = lax.psum(1, axis_name)
    sc = _scale(q, scale)
    perm = [(j, (j + 1) % n) for j in range(n)]
    synthesized_mask = kv_valid is None
    if synthesized_mask:
        kv_valid = jnp.ones(k.shape[1], bool)

    def step(i, carry):
        m, l, o, kb, vb, maskb = carry
        m, l, o = _online_block(q, m, l, o, kb, vb, sc, valid=maskb)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        maskb = lax.ppermute(maskb, axis_name, perm)
        return m, l, o, kb, vb, maskb

    # mark the constant-valued init as device-varying so the loop carry
    # type-checks under shard_map's varying-axis typing (pcast is the
    # non-deprecated spelling of pvary from jax 0.9)
    if hasattr(lax, 'pcast'):
        def cast(t):
            return lax.pcast(t, axis_name, to='varying')
    elif hasattr(lax, 'pvary'):
        def cast(t):
            return lax.pvary(t, axis_name)
    else:
        # jax 0.4.x shard_map has no varying-axis typing; no cast needed
        def cast(t):
            return t
    m, l, o = (cast(t) for t in _online_init(q))
    if synthesized_mask:   # caller-provided masks are already device-varying
        kv_valid = cast(kv_valid)
    # n-1 rotations interleaved with compute; the final block needs no send.
    m, l, o, kb, vb, maskb = lax.fori_loop(
        0, n - 1, step, (m, l, o, k, v, kv_valid))
    m, l, o = _online_block(q, m, l, o, kb, vb, sc, valid=maskb)
    return (o / l).astype(q.dtype)
