"""Targeted matmul-precision pinning for mixed-precision graphs.

The parity bar (feature rel L2 ≤ 1e-3 vs the reference, BASELINE.json)
forces ``precision=highest`` when applied globally — bf16 MXU passes drift
1.3e-2 through the fused RAFT→quantize→I3D path because the flow uint8
quantization cliff amplifies small flow errors. But the drift is not
uniform across the graph: a few numerically sensitive sub-graphs (the
correlation volume, the per-iteration refinement whose error compounds over
20 GRU steps, the I3D towers reading the quantized flow) dominate it, while
the one-shot encoders tolerate fast passes.

``pins`` name sub-graphs to run at a DIFFERENT matmul precision than the
ambient one: a tuple of (component, precision) pairs — hashable so it can
ride jit static args and participate in the compile cache key. Components
wired up:

  * raft: 'encoder' (fnet/cnet), 'corr' (pyramid build + lookup),
    'iter' (motion encoder + GRU + flow/mask heads), 'upsample';
  * the fused I3D step: 'i3d' (both towers).

``precision='mixed'`` in an extraction config = ambient 'default' (fast
MXU passes) + the measured-safe pins (MIXED_PINS below, tuned on TPU by
tools/precision_study.py).
"""
from __future__ import annotations

from contextlib import nullcontext
from typing import Dict, Optional, Tuple, Union

import jax

Pins = Tuple[Tuple[str, str], ...]

# The 'mixed' policy, tuned by tools/precision_study.py on v5e (fused
# two-stream path, drift = feature rel L2 vs all-float32 on identical
# inputs/weights): ambient 'high' (3-pass bf16 ≈ fp32 to ~2^-21 per
# matmul) measures 8.4e-4 flow / 1.3e-4 rgb — under the ≤1e-3 parity bar —
# at ~1.9x the float32 rate (14.9 vs 7.9 clips/s, quiet-host bench.py at
# stack 16 / 224px). No
# sub-graph survives 1-pass: encoder-at-default alone is 1.04e-2, and
# corr-at-default under ambient high is 4.4e-3 (the flow-quantization
# cliff amplifies both). So 'mixed' is ambient 'high' with no down-pins;
# the pins machinery stays for study sweeps and future per-op tuning.
MIXED_AMBIENT = 'high'
MIXED_PINS: Pins = ()


def normalize_pins(pins: Union[None, Pins, Dict[str, str]]) -> Optional[Pins]:
    """dict/tuple → canonical sorted tuple (None stays None)."""
    if pins is None:
        return None
    items = pins.items() if isinstance(pins, dict) else pins
    return tuple(sorted((str(k), str(v)) for k, v in items))


def pin_scope(pins: Optional[Pins], component: str):
    """Trace-time context: matmul precision override for one sub-graph.

    Returns a null context when the component is not pinned, so call sites
    cost nothing in the common (unpinned) case.
    """
    if pins:
        for name, prec in pins:
            if name == component:
                return jax.default_matmul_precision(prec)
    return nullcontext()
