"""Targeted matmul-precision pinning for mixed-precision graphs.

The parity bar (feature rel L2 ≤ 1e-3 vs the reference, BASELINE.json)
forces ``precision=highest`` when applied globally — bf16 MXU passes drift
1.3e-2 through the fused RAFT→quantize→I3D path because the flow uint8
quantization cliff amplifies small flow errors. But the drift is not
uniform across the graph: a few numerically sensitive sub-graphs (the
correlation volume, the per-iteration refinement whose error compounds over
20 GRU steps, the I3D towers reading the quantized flow) dominate it, while
the one-shot encoders tolerate fast passes.

``pins`` name sub-graphs to run at a DIFFERENT matmul precision than the
ambient one: a tuple of (component, precision) pairs — hashable so it can
ride jit static args and participate in the compile cache key. Components
wired up:

  * raft: 'encoder' (fnet/cnet), 'corr' (pyramid build + lookup),
    'iter' (motion encoder + GRU + flow/mask heads), 'upsample';
  * the fused I3D step: 'i3d' (both towers).

``precision='mixed'`` in an extraction config = ambient 'default' (fast
MXU passes) + the measured-safe pins (MIXED_PINS below, tuned on TPU by
tools/precision_study.py).
"""
from __future__ import annotations

from contextlib import nullcontext
from typing import Dict, Optional, Tuple, Union

import numpy as np

Pins = Tuple[Tuple[str, str], ...]

# The 'mixed' policy, tuned by tools/precision_study.py on v5e (fused
# two-stream path, drift = feature rel L2 vs all-float32 on identical
# inputs/weights): ambient 'high' (3-pass bf16 ≈ fp32 to ~2^-21 per
# matmul) measures 8.4e-4 flow / 1.3e-4 rgb — under the ≤1e-3 parity bar —
# at ~1.9x the float32 rate (14.9 vs 7.9 clips/s, quiet-host bench.py at
# stack 16 / 224px). No
# sub-graph survives 1-pass: encoder-at-default alone is 1.04e-2, and
# corr-at-default under ambient high is 4.4e-3 (the flow-quantization
# cliff amplifies both). So 'mixed' is ambient 'high' with no down-pins;
# the pins machinery stays for study sweeps and future per-op tuning.
MIXED_AMBIENT = 'high'
MIXED_PINS: Pins = ()


def normalize_pins(pins: Union[None, Pins, Dict[str, str]]) -> Optional[Pins]:
    """dict/tuple → canonical sorted tuple (None stays None)."""
    if pins is None:
        return None
    items = pins.items() if isinstance(pins, dict) else pins
    return tuple(sorted((str(k), str(v)) for k, v in items))


def pin_scope(pins: Optional[Pins], component: str):
    """Trace-time context: matmul precision override for one sub-graph.

    Returns a null context when the component is not pinned, so call sites
    cost nothing in the common (unpinned) case.
    """
    if pins:
        for name, prec in pins:
            if name == component:
                import jax
                return jax.default_matmul_precision(prec)
    return nullcontext()


# -- the compute_dtype fast lanes (the precision ladder) ---------------------
#
# ``compute_dtype=`` is ORTHOGONAL to the matmul ``precision=`` knob above:
# ``precision`` selects how many bf16 passes each fp32 matmul executes on
# the MXU (the *arithmetic* of an fp32-resident graph), while
# ``compute_dtype`` changes what is *stored*. The ladder:
#
#   * ``bfloat16`` — params cast bf16 once at transplant time (half the
#     HBM residency and H2D bytes) and activations flow bf16 through the
#     whole step, with fp32 accumulation islands where parity demands it
#     (softmax / LayerNorm / BatchNorm statistics, global pooling —
#     ops/nn.py, the model layer_norm homes).
#   * ``int8`` — conv/linear weights quantized per-output-channel
#     symmetric int8 at transplant time (ops/quant.py; a QUARTER of the
#     fp32 param bytes) and dequantized in-graph at use; activations stay
#     float32, so the drift is pure weight rounding.
#
# Feature outputs are cast back to float32 at the step epilogue, so the
# on-disk contract is unchanged; the *values* differ from the fp32 lane
# within the per-family bounds below.

COMPUTE_DTYPES = ('float32', 'bfloat16', 'int8')

# Per-family parity bounds for the bf16 lane: feature rel-L2 error vs the
# float32 lane on identical inputs/weights — the same metric the repo's
# reference-parity bar uses (BASELINE.json), PARITY.md-style pinned.
# Measured by tests/test_precision.py (CPU XLA bf16, random weights, the
# REAL jitted steps) and re-asserted there on every run; the bench's
# *_bf16_* rungs record the measured error next to the speedup so a
# committed number is checkable against its bound. Bounds carry ~3x
# headroom over the measured drift (max-abs error is recorded alongside
# for absolute context, but scales with feature magnitude — rel-L2 is
# the stable pin across weights/geometry).
#
# NOTE the lane's honest trade: ~0.5-2e-2 rel-L2 is an order past the
# <=1e-3 reference-parity bar — the bf16 lane is for throughput-bound
# embedding consumers (retrieval, dedup, clustering), not for
# reference-parity reproduction; precision=mixed remains the
# parity-grade fast mode.
BF16_REL_L2_BOUNDS: Dict[str, float] = {
    'r21d': 1.5e-2,    # measured 4.9e-3 (stack 10, 64x86, CPU XLA bf16)
    's3d': 2e-2,       # measured 5.9e-3 (in-graph scale-resize rides bf16)
    'resnet': 2e-2,    # measured 5.8e-3 (resnet18; BN-fold islands fp32)
    'clip': 3e-2,      # measured 1.0e-2 (ViT-B/32; LN/softmax islands)
    'timm': 5e-2,      # measured 1.8e-2 (vit_base_patch16_224)
    'vggish': 2.5e-2,  # measured 7.2e-3 (plain conv/relu VGG)
}

# Families that REFUSE the knob, with the measured drift that disqualifies
# them (docs/benchmarks.md precision ladders): the fused i3d flow path
# amplifies flow error through the uint8 quantization cliff, and raft's
# raw flow output compounds bf16 error over 20 GRU refinement iterations —
# neither meets its parity bound under bf16 storage, so the knob fails the
# BUILD with a structured error instead of shipping out-of-bound features.
# The int8 weight lane's parity bounds (compute_dtype=int8): post-training
# per-output-channel symmetric weight quantization (ops/quant.py) with
# fp32 activations — so the drift is pure weight rounding, not compounding
# activation error, and stays in the same order as bf16 for the framewise
# backbones the lane exists for (bandwidth-bound at 2500+ frames/s;
# quarter-size params). Same measurement protocol and ~3x headroom as
# BF16_REL_L2_BOUNDS above (tests/test_precision.py, CPU XLA, random
# weights, the REAL jitted steps); tools/calibrate_int8.py re-measures
# against real checkpoints and pins the per-tensor scale tables.
INT8_REL_L2_BOUNDS: Dict[str, float] = {
    'resnet': 5e-2,   # measured 1.5e-2 (resnet18; BN params stay fp32)
    'clip': 3.5e-2,   # measured 1.1e-2 (ViT-B/32; LN/proj/embeds fp32)
    'timm': 7.5e-2,   # measured 2.5e-2 (vit_base_patch16_224)
}

# Families that REFUSE compute_dtype=int8, with the reason (same contract
# as BF16_REFUSALS: the knob fails the BUILD with a structured error).
# i3d/raft fail for a STRICTER version of their bf16 reasons — weight
# rounding feeds the same error amplifiers (the flow uint8-quantization
# cliff, 20 GRU refinement iterations) that already disqualify bf16's
# smaller perturbation. The video families (r21d/s3d/vggish) are not
# bandwidth-bound at their geometries, so nobody has measured them a
# bound — they fall through to the generic no-measured-bound refusal.
INT8_REFUSALS: Dict[str, str] = {
    'i3d': ('the fused RAFT->quantize->I3D flow path already measures '
            '1.24e-2 drift under bf16 (docs/benchmarks.md precision '
            'ladder) vs the <=1e-3 parity bound, and int8 weight '
            'rounding is a coarser perturbation through the same flow '
            'uint8-quantization cliff; use precision=mixed (8.5e-4) '
            "for i3d's fast lane instead"),
    'raft': ('raw flow output compounds weight-rounding error across 20 '
             'GRU refinement iterations (the corr/iter sub-graphs '
             'measure >=4.4e-3 under fast passes, docs/benchmarks.md) '
             'vs the <=1e-3 parity bound; use precision=mixed for raft '
             'instead'),
}

BF16_REFUSALS: Dict[str, str] = {
    'i3d': ('the fused RAFT->quantize->I3D flow path measures 1.24e-2 '
            'feature drift under 1-pass bf16 (docs/benchmarks.md '
            'precision ladder) vs the <=1e-3 parity bound — the flow '
            'uint8-quantization cliff amplifies bf16 error; use '
            "precision=mixed (3-pass bf16 matmuls, 8.5e-4) for i3d's "
            'fast lane instead'),
    'raft': ('raw flow output compounds bf16 error across 20 GRU '
             'refinement iterations (corr/iter sub-graphs measure '
             '>=4.4e-3 under fast passes, docs/benchmarks.md) vs the '
             '<=1e-3 parity bound; use precision=mixed for raft '
             'instead'),
}


class ComputeDtypeError(ValueError):
    """A family refused (or doesn't know) the requested compute_dtype."""


def check_compute_dtype(feature_type: Optional[str],
                        compute_dtype: str) -> str:
    """Validate the knob at BUILD time (config.sanity_check): the value
    must be known, and a fast-lane ask (bfloat16 / int8) against a family
    outside the lane's opt-in registry set raises a structured error
    naming the parity bound it would break — a serve submit then fails
    its build with this message instead of a worker shipping drifted
    features. The refusal message echoes the REQUESTED dtype (not a
    hardcoded lane name — tests/test_precision.py pins this for both
    fast lanes)."""
    if compute_dtype in ('float8', 'fp8', 'float8_e4m3fn', 'float8_e5m2'):
        # the rung below int8 is not a measurement gap, it is a backend
        # gap: structured not-yet so the remediation is honest
        raise ComputeDtypeError(
            f'compute_dtype={compute_dtype} is not supported yet: fp8 '
            f'param storage is gated on XLA backend support for fp8 '
            f'convert/dot lowering on the deployed runtimes — the '
            f'precision ladder currently ends at int8 weight '
            f'quantization (compute_dtype=int8, ops/quant.py)')
    if compute_dtype not in COMPUTE_DTYPES:
        raise ComputeDtypeError(
            f'compute_dtype must be one of {COMPUTE_DTYPES}; '
            f'got {compute_dtype!r}')
    if compute_dtype != 'float32' and feature_type is not None:
        if compute_dtype == 'bfloat16':
            from video_features_tpu.registry import BF16_FEATURES
            accepted, refusals, registry_name = (
                BF16_FEATURES, BF16_REFUSALS, 'registry.BF16_FEATURES')
        else:
            from video_features_tpu.registry import INT8_FEATURES
            accepted, refusals, registry_name = (
                INT8_FEATURES, INT8_REFUSALS, 'registry.INT8_FEATURES')
        if feature_type not in accepted:
            why = refusals.get(
                feature_type,
                f'{feature_type} has no measured {compute_dtype} parity '
                f'bound (tests/test_precision.py) — a family must opt in '
                f'via {registry_name} with a pinned bound before the '
                f'fast lane is allowed to serve its features')
            raise ComputeDtypeError(
                f'compute_dtype={compute_dtype} is refused for '
                f'feature_type={feature_type}: {why}')
    return compute_dtype


def param_np_dtype(compute_dtype: str) -> np.dtype:
    """The numpy dtype params are STORED in for this lane — what the
    transplant layer casts checkpoints to, so bf16 params are bf16 in
    HBM from the first ``device_put``, not cast per-step. For the int8
    lane this is the STORAGE dtype of the quantized weight payload: the
    transplant layer treats it as "quantize eligible weights, float32
    for the rest" (ops/quant.quantize_flat), not a blanket astype.
    Dispatch is exhaustive over COMPUTE_DTYPES — an unrecognized lane
    raises instead of silently storing float32 (the pre-int8 fall-through
    shipped full-size params under a lane nobody validated)."""
    if compute_dtype == 'float32':
        return np.dtype(np.float32)
    if compute_dtype == 'bfloat16':
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if compute_dtype == 'int8':
        return np.dtype(np.int8)
    raise ComputeDtypeError(
        f'param_np_dtype: unknown compute_dtype {compute_dtype!r} '
        f'(known: {COMPUTE_DTYPES})')


def rel_l2(reference: np.ndarray, candidate: np.ndarray) -> float:
    """||candidate - reference||2 / ||reference||2 — the ONE definition
    of the parity metric the bounds above pin, shared by the tests, the
    bench *_bf16_* error rungs, and the dryrun gate so no two consumers
    can disagree about what "under the bound" means."""
    a = np.asarray(reference, np.float64).ravel()
    b = np.asarray(candidate, np.float64).ravel()
    denom = float(np.linalg.norm(a))
    return float(np.linalg.norm(b - a)) / max(denom, 1e-30)


def features_to_f32(x):
    """Step-epilogue cast: feature outputs always leave the device as
    float32, whatever lane computed them (the on-disk .npy contract and
    every consumer's dtype expectation stay lane-independent). A no-op —
    emitting NO convert into the lowered program, so the float32 lane's
    StableHLO stays byte-identical to the pre-knob programs — when the
    input is already float32."""
    import jax.numpy as jnp
    if x.dtype == jnp.float32:
        return x
    return x.astype(jnp.float32)
