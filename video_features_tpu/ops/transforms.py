"""Tensor transforms (device-side, jit-able) + host-side PIL helpers.

Numerics-parity notes vs the reference transform chain
(reference models/transforms.py, 307 LoC):
  * frames here are channels-last (T, H, W, C) float32 — the reference's
    CFHW/CHW permutes (:34-35, :152-155) are layout choices of torch and do
    not exist in this framework;
  * ``resize_bilinear`` matches torch ``F.interpolate(mode='bilinear',
    align_corners=False)`` = half-pixel centers, no antialias — what the
    reference `Resize` uses for tensors (:76-96);
  * PIL-style short-side resize (the reference's i3d/RAFT frame prep
    ``ResizeImproved`` :234-242) stays on the host where PIL gives exact
    parity; it runs before frames are stacked for the device.
"""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def to_float_zero_one(x: Array, dtype=None) -> Array:
    """uint8 [0,255] → float [0,1] (reference transforms.py:34-35 numerics).

    ``dtype`` selects the activation dtype the device edge casts to —
    the bf16 fast lane (``compute_dtype=bfloat16``) passes ``bfloat16``
    here so the whole step runs bf16 from the first op; None keeps the
    historical float32 (byte-identical graph for every existing caller).
    """
    return jnp.asarray(x, jnp.float32 if dtype is None else dtype) / 255.0


def scale_to_pm1(x: Array) -> Array:
    """[0,255] → [-1,1] via 2x/255 - 1 (reference transforms.py:146-149).

    Accepts uint8 (the extractors ship frames to the device undilated) or
    float input; either way the result is float32.
    """
    return jnp.asarray(x, jnp.float32) * (2.0 / 255.0) - 1.0


def normalize(x: Array, mean: Sequence[float], std: Sequence[float]) -> Array:
    """Per-channel (x - mean) / std over the trailing axis."""
    mean = jnp.asarray(mean, x.dtype)
    std = jnp.asarray(std, x.dtype)
    return (x - mean) / std


def resize_bilinear(x: Array, size: Tuple[int, int]) -> Array:
    """Bilinear resize of (..., H, W, C) to (..., *size, C), half-pixel centers,
    no antialias — torch ``F.interpolate(..., align_corners=False)`` parity."""
    *lead, h, w, c = x.shape
    out_shape = (*lead, *size, c)
    return jax.image.resize(x, out_shape, method='bilinear', antialias=False)


def _interp_matrix(in_len: int, out_len: int, scale: float) -> np.ndarray:
    """(out_len, in_len) bilinear interpolation matrix for torch's
    align_corners=False grid at a GIVEN scale: src = (dst+0.5)/scale - 0.5,
    clamped to [0, in_len-1]."""
    src = np.maximum((np.arange(out_len) + 0.5) / scale - 0.5, 0.0)
    src = np.minimum(src, in_len - 1)
    lo = np.floor(src).astype(np.int64)
    hi = np.minimum(lo + 1, in_len - 1)
    w = (src - lo).astype(np.float32)
    m = np.zeros((out_len, in_len), np.float32)
    m[np.arange(out_len), lo] += 1.0 - w
    m[np.arange(out_len), hi] += w
    return m


def resize_bilinear_scale(x: Array, size: Tuple[int, int],
                          scale: float) -> Array:
    """Bilinear resize whose sampling grid uses an explicitly GIVEN scale.

    torch's ``F.interpolate(..., scale_factor=s, recompute_scale_factor=
    False)`` — the reference's short-side ``Resize(int)``
    (models/transforms.py:76-96) — maps output→input coordinates with the
    *requested* scale, not ``out_len/in_len``; the two grids differ on the
    non-short axis (e.g. 320→298 at scale 224/240: 0.9333 vs 0.93125, up
    to ~0.7 px at the right edge — a 1e-2 feature drift through S3D).
    Implemented as two small dense interpolation matmuls (MXU-friendly,
    no gathers); the matrices are trace-time constants per geometry.
    """
    *lead, h, w, c = x.shape
    # matrices follow x's dtype so the bf16 lane's einsums stay bf16
    # instead of silently promoting the activations back to fp32 (for
    # float32 input this is exactly the constant jnp.asarray always built)
    mh = jnp.asarray(_interp_matrix(h, size[0], scale), x.dtype)
    mw = jnp.asarray(_interp_matrix(w, size[1], scale), x.dtype)
    # (..., H, W, C): contract H with mh, then W with mw
    out = jnp.einsum('oh,...hwc->...owc', mh, x)
    return jnp.einsum('pw,...owc->...opc', mw, out)


PIL_PRECISION_BITS = 32 - 8 - 2   # Pillow Resample.c PRECISION_BITS


def _pil_bilinear_coeff_matrix(in_size: int, out_size: int) -> np.ndarray:
    """Pillow's fixed-point BILINEAR resample coefficients as a dense
    (out_size, in_size) int64 matrix.

    Bit-for-bit the arithmetic of Pillow's ``precompute_coeffs`` +
    ``normalize_coeffs_8bpc`` (Resample.c): triangle filter widened by
    the scale when downscaling, per-output-pixel window [xmin, xmax)
    from ``int(center ± support + 0.5)``, weights normalized in double
    then quantized to ``int(±0.5 + k·2^22)``. Validated bit-exact
    against PIL itself in tests/test_device_resize.py.
    """
    scale = in_size / out_size
    filterscale = max(scale, 1.0)
    support = filterscale              # bilinear support = 1.0 · filterscale
    ss = 1.0 / filterscale
    M = np.zeros((out_size, in_size), np.int64)
    for xx in range(out_size):
        center = (xx + 0.5) * scale
        xmin = max(int(center - support + 0.5), 0)
        xmax = min(int(center + support + 0.5), in_size)
        x = np.arange(xmin, xmax)
        k = np.maximum(0.0, 1.0 - np.abs((x - center + 0.5) * ss))
        tot = k.sum()
        if tot != 0.0:
            k = k / tot
        M[xx, xmin:xmax] = np.floor(np.where(
            k < 0, -0.5 + k * (1 << PIL_PRECISION_BITS),
            0.5 + k * (1 << PIL_PRECISION_BITS))).astype(np.int64)
    return M


def _limb_split(M: np.ndarray) -> np.ndarray:
    """(out, in) non-negative int64 → (3, out, in) float32 byte limbs,
    M = limbs[2]·2^16 + limbs[1]·2^8 + limbs[0]. Each limb ≤ 255, so a
    limb×uint8-pixel matmul stays exact in float32: products < 2^17, and
    fp32 represents integers exactly only up to 2^24, so the real
    constraint is on the window sum — nnz·255·255 < 2^24 (asserted
    below; at the widest window this allows, 258 taps, the worst case is
    16,776,450, just 766 under the limit — zero headroom, which is why
    the assert derives from the constraint instead of pinning a tap
    count). This is how the integer resample rides the MXU without
    integer matmul support."""
    assert (M >= 0).all(), 'bilinear coefficients are non-negative'
    nnz_per_row = int((M != 0).sum(1).max())
    assert nnz_per_row * 255 * 255 < 2 ** 24, \
        f'window too wide for exact fp32 limb sums: {nnz_per_row} taps'
    return np.stack([(M & 0xFF), (M >> 8) & 0xFF, (M >> 16) & 0xFF],
                    0).astype(np.float32)


def _pil_resample_axis(x: Array, limbs: np.ndarray, axis_h: bool) -> Array:
    """One Pillow 8bpc resample pass over H (axis_h) or W of
    (..., H, W, C) uint8-valued input; returns uint8.

    Exactly ``clip8(2^21 + Σ pixel·coeff)`` with the sum reassembled
    from the three exact fp32 limb matmuls in int32 (max accumulator
    255·2^22 < 2^31)."""
    lm = jnp.asarray(limbs)                      # (3, out, in) f32
    xf = jnp.asarray(x, jnp.float32)
    eq = 'loh,...hwc->l...owc' if axis_h else 'low,...hwc->l...hoc'
    # precision stays PINNED at HIGHEST regardless of the ambient matmul
    # policy or the compute_dtype lane: this einsum is exact INTEGER
    # arithmetic riding the MXU — byte limbs x uint8 pixels, every
    # product < 2^17 and every window sum < 2^24, representable exactly
    # ONLY in full fp32 (see _limb_split). A bf16 pass would corrupt the
    # fixed-point limbs and break the bit-exact-Pillow contract
    # (tests/test_device_resize.py), so the bf16 fast lane deliberately
    # does NOT reach inside this resample — it is exact at any lane.
    parts = jnp.einsum(eq, lm, xf,
                       precision=jax.lax.Precision.HIGHEST)
    p = parts.astype(jnp.int32)
    acc = (p[0] + (p[1] << 8) + (p[2] << 16)
           + (1 << (PIL_PRECISION_BITS - 1)))
    out = jnp.clip(acc >> PIL_PRECISION_BITS, 0, 255)
    out = jnp.where(acc >= (1 << PIL_PRECISION_BITS << 8), 255, out)
    out = jnp.where(acc <= 0, 0, out)
    return out.astype(jnp.uint8)


def pil_resize_bilinear_device(x: Array, size: Tuple[int, int]) -> Array:
    """In-graph BIT-EXACT Pillow bilinear resize: (..., H, W, C)
    uint8-valued → (..., oh, ow, C) uint8.

    Reproduces ``PIL.Image.resize(size, BILINEAR)`` — the reference's
    host-side ``ResizeImproved`` numerics (reference
    models/transforms.py:191-242) — inside the XLA graph, including the
    horizontal-then-vertical pass order and the uint8 intermediate
    between passes. This is what makes ``device_resize=true``
    parity-grade: the device pipeline sees the SAME pixels the host-PIL
    pipeline produces, so the flow-quantization cliff costs nothing.
    Coefficient matrices are trace-time constants per geometry.
    """
    h, w = x.shape[-3], x.shape[-2]
    oh, ow = size
    if ow != w:
        x = _pil_resample_axis(x, _limb_split(
            _pil_bilinear_coeff_matrix(w, ow)), axis_h=False)
    if oh != h:
        x = _pil_resample_axis(x, _limb_split(
            _pil_bilinear_coeff_matrix(h, oh)), axis_h=True)
    return jnp.asarray(x, jnp.uint8)


def center_crop(x: Array, size: Union[int, Tuple[int, int]]) -> Array:
    """Center crop of (..., H, W, C); torch CenterCrop offset convention
    (round-half-down via int division)."""
    if isinstance(size, int):
        size = (size, size)
    th, tw = size
    h, w = x.shape[-3], x.shape[-2]
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return x[..., i:i + th, j:j + tw, :]


def clamp(x: Array, min_val: float, max_val: float) -> Array:
    return jnp.clip(x, min_val, max_val)


def flow_to_uint8_levels(x: Array, bound: float = 20.0) -> Array:
    """Flow [-bound, bound] → quantized [0, 255] levels then back to float.

    The kinetics-i3d flow recipe, bit-matching reference transforms.py:175
    `ToUInt8`: ``round(128 + 255/(2·bound)·x)`` — the OFFSET IS 128, not the
    symmetric 127.5 a textbook quantizer (or the reference's own
    "[-20, 20] -> [0, 255]" comment) would suggest, so zero flow lands
    exactly on level 128 and the clamp bounds map to the half-open 0.5 /
    255.5 rounding edges. Using 127.5 here shifts ~half of ALL pixels one
    level (wherever frac(6.375·x) < 0.5) — a systematic ~3e-3 feature
    drift through the flow tower that round-2's golden misattributed to
    random-weight quantization noise. Keeps float dtype so the subsequent
    ScaleTo1_1 sees the same values torch's tensor held (including 256.0
    for exactly-saturated positive flow, which torch's round-half-even
    produces and never re-clips).
    """
    x = jnp.clip(x, -bound, bound)
    return jnp.round(128.0 + x * (255.0 / (2.0 * bound)))


# Host-side (PIL/NumPy) transforms live in the jax-free
# ``ops.host_transforms`` module so decode-farm worker processes can
# import them without pulling jax; re-exported here so every existing
# device-side import site keeps working.
from video_features_tpu.ops.host_transforms import (  # noqa: F401,E402
    center_crop_host, pil_edge_resize_geometry, resize_pil,
    short_side_resize_pil,
)
