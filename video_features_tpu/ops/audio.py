"""Audio DSP: waveform → log-mel examples for VGGish.

Numerics re-implementation of the chain behind the reference's
preprocessing (reference models/vggish/vggish_src/mel_features.py 223 LoC,
vggish_input.py 89 LoC): strided framing with floor-truncated tails,
periodic Hann window, magnitude rFFT at the next power of two, an HTK
triangular mel filterbank with a zeroed DC bin, log with offset 0.01, and
0.96 s non-overlapping 96×64 examples.

This runs on the host (float64, exactly like the reference's numpy) — the
DSP is microseconds per clip; the VGG net is the device work.

Resampling parity: the reference resamples any non-16 kHz wav with
``resampy.resample`` (kaiser_best) — reference
models/vggish/vggish_src/vggish_input.py:47-49, resampy pinned 0.4.2 in
its conda_env.yml. :func:`resample` here implements that exact algorithm
(windowed-sinc interpolation with resampy's published kaiser_best filter
parameters) in vectorized numpy — see :func:`resample_kaiser`. The
previous scipy ``resample_poly`` substitute is kept as
``method='polyphase'`` for comparison; its feature-level divergence is
quantified in tests/test_audio_resample.py.
"""
from __future__ import annotations

from fractions import Fraction
from typing import Optional

import numpy as np

SAMPLE_RATE = 16000
STFT_WINDOW_SECS = 0.025
STFT_HOP_SECS = 0.010
NUM_MEL_BINS = 64
MEL_MIN_HZ = 125.0
MEL_MAX_HZ = 7500.0
LOG_OFFSET = 0.01
EXAMPLE_WINDOW_SECS = 0.96
EXAMPLE_HOP_SECS = 0.96

_MEL_BREAK_HZ = 700.0
_MEL_HIGH_Q = 1127.0


def frame(data: np.ndarray, window_length: int, hop_length: int) -> np.ndarray:
    """(T, ...) → (num_frames, window_length, ...); incomplete tails dropped."""
    num_frames = 1 + int(np.floor((data.shape[0] - window_length) / hop_length))
    shape = (num_frames, window_length) + data.shape[1:]
    strides = (data.strides[0] * hop_length,) + data.strides
    return np.lib.stride_tricks.as_strided(data, shape=shape, strides=strides)


def periodic_hann(window_length: int) -> np.ndarray:
    """Full-cycle (period-N) raised cosine — NOT numpy's symmetric hanning."""
    return 0.5 - 0.5 * np.cos(2 * np.pi / window_length
                              * np.arange(window_length))


def stft_magnitude(signal: np.ndarray, fft_length: int, hop_length: int,
                   window_length: int) -> np.ndarray:
    frames = frame(signal, window_length, hop_length)
    return np.abs(np.fft.rfft(frames * periodic_hann(window_length),
                              int(fft_length)))


def hertz_to_mel(frequencies_hertz):
    return _MEL_HIGH_Q * np.log(1.0 + np.asarray(frequencies_hertz)
                                / _MEL_BREAK_HZ)


def mel_matrix(num_mel_bins: int = NUM_MEL_BINS,
               num_spectrogram_bins: int = 257,
               audio_sample_rate: float = SAMPLE_RATE,
               lower_edge_hertz: float = MEL_MIN_HZ,
               upper_edge_hertz: float = MEL_MAX_HZ) -> np.ndarray:
    """(num_spectrogram_bins, num_mel_bins) triangular HTK filterbank,
    linear in mel space, DC bin zeroed."""
    nyquist = audio_sample_rate / 2.0
    if not 0.0 <= lower_edge_hertz < upper_edge_hertz <= nyquist:
        raise ValueError('bad mel band edges')
    spec_mel = hertz_to_mel(np.linspace(0.0, nyquist, num_spectrogram_bins))
    edges = np.linspace(hertz_to_mel(lower_edge_hertz),
                        hertz_to_mel(upper_edge_hertz), num_mel_bins + 2)
    lower = (spec_mel[:, None] - edges[None, :-2]) / (edges[1:-1] - edges[:-2])
    upper = (edges[None, 2:] - spec_mel[:, None]) / (edges[2:] - edges[1:-1])
    weights = np.maximum(0.0, np.minimum(lower, upper))
    weights[0, :] = 0.0
    return weights


def log_mel_spectrogram(data: np.ndarray,
                        audio_sample_rate: float = SAMPLE_RATE) -> np.ndarray:
    window_length = int(round(audio_sample_rate * STFT_WINDOW_SECS))
    hop_length = int(round(audio_sample_rate * STFT_HOP_SECS))
    fft_length = 2 ** int(np.ceil(np.log(window_length) / np.log(2.0)))
    spec = stft_magnitude(data, fft_length, hop_length, window_length)
    mel = spec @ mel_matrix(num_spectrogram_bins=spec.shape[1],
                            audio_sample_rate=audio_sample_rate)
    return np.log(mel + LOG_OFFSET)


# resampy 0.4.2 kaiser_best filter parameters (resampy/filters.py
# sinc_window + the shipped kaiser_best.npz generation constants): 64
# zero-crossings, 2^9 table entries per crossing, Kaiser window
# beta 14.769656459379492, roll-off 0.9475937167399596.
KAISER_BEST = dict(num_zeros=64, precision=9,
                   beta=14.769656459379492, rolloff=0.9475937167399596)

_FILTER_CACHE: dict = {}


def sinc_window(num_zeros: int, precision: int, beta: float,
                rolloff: float) -> tuple:
    """Right wing of resampy's interpolation filter (filters.sinc_window):
    a roll-off-scaled sinc sampled at 2^precision points per zero
    crossing, tapered by the right half of a Kaiser window. Returns
    (interp_win, num_table)."""
    from scipy.signal.windows import kaiser
    num_table = 2 ** precision
    n = num_table * num_zeros
    sinc_win = rolloff * np.sinc(
        rolloff * np.linspace(0, num_zeros, num=n + 1, endpoint=True))
    taper = kaiser(2 * n + 1, beta)[n:]
    return taper * sinc_win, num_table


def _interp_tables(sample_ratio: float) -> tuple:
    """(interp_win, interp_delta, num_table) for one ratio — the filter is
    pre-scaled by the ratio when downsampling (anti-aliasing), and
    interp_delta holds first differences for linear interpolation between
    table entries (resampy core.resample)."""
    if 'kaiser_best' not in _FILTER_CACHE:
        _FILTER_CACHE['kaiser_best'] = sinc_window(**KAISER_BEST)
    win, num_table = _FILTER_CACHE['kaiser_best']
    if sample_ratio < 1:
        win = win * sample_ratio
    delta = np.zeros_like(win)
    delta[:-1] = np.diff(win)
    return win, delta, num_table


def resample_kaiser(data: np.ndarray, sr: int,
                    target_sr: int = SAMPLE_RATE) -> np.ndarray:
    """resampy-parity resampling (resampy 0.4.2 resample_f semantics,
    kaiser_best filter), vectorized over output samples in chunks.

    For each output time t (in input-sample units) the two filter wings
    accumulate ``win[offset + i*step] + eta*delta[...]`` against the
    input samples left/right of t — the exact windowed-sinc interpolation
    loop of resampy/interpn.py, with the per-output-sample inner loops
    turned into masked (chunk, taps) gathers. The literal-transcription
    mirror in tests/test_audio_resample.py pins equivalence."""
    ratio = Fraction(int(target_sr), int(sr))   # gcd-reduced, exact
    sample_ratio = float(ratio)
    n_in = data.shape[0]
    # resampy ≥0.4.0 output length: shape[axis] * sr_new // sr_orig
    # (integer floor — its 0.4.0 rounding fix); exact-int via the reduced
    # fraction, which floors identically.
    n_out = n_in * ratio.numerator // ratio.denominator
    win, delta, num_table = _interp_tables(sample_ratio)
    scale = min(1.0, sample_ratio)
    index_step = int(scale * num_table)
    nwin = win.shape[0]
    max_taps = nwin // index_step + 1
    out = np.zeros(n_out, dtype=np.float64)
    x = np.asarray(data, dtype=np.float64)
    taps = np.arange(max_taps)

    def wing(n, offset, eta, limit):
        """Masked gather-accumulate of one filter wing for a chunk:
        sum_i (win[offset + i*step] + eta*delta[...]) * x[n ± i]."""
        idx = offset[:, None] + taps[None, :] * index_step
        valid = taps[None, :] < limit[:, None]
        idx = np.minimum(idx, nwin - 1)
        w = (win[idx] + eta[:, None] * delta[idx]) * valid
        src = np.clip(n, 0, n_in - 1)
        return np.einsum('ct,ct->c', w, x[src])

    chunk = 1 << 15
    for start in range(0, n_out, chunk):
        t_idx = np.arange(start, min(start + chunk, n_out))
        time_register = t_idx / sample_ratio
        n = time_register.astype(np.int64)
        frac = scale * (time_register - n)
        index_frac = frac * num_table
        offset = index_frac.astype(np.int64)
        eta = index_frac - offset
        i_max = np.minimum(n + 1, (nwin - offset) // index_step)
        left = wing(n[:, None] - taps[None, :], offset, eta, i_max)
        frac_r = scale - frac
        index_frac = frac_r * num_table
        offset = index_frac.astype(np.int64)
        eta = index_frac - offset
        k_max = np.minimum(n_in - n - 1, (nwin - offset) // index_step)
        right = wing(n[:, None] + 1 + taps[None, :], offset, eta, k_max)
        out[t_idx] = left + right
    return out


def resample(data: np.ndarray, sr: int, target_sr: int = SAMPLE_RATE,
             method: str = 'kaiser_best') -> np.ndarray:
    """Resample to ``target_sr``. ``kaiser_best`` (default) is the
    reference-parity path; ``polyphase`` keeps the earlier scipy
    resampler for comparison."""
    if method == 'kaiser_best':
        return resample_kaiser(data, sr, target_sr)
    from scipy.signal import resample_poly
    ratio = Fraction(target_sr, sr)
    return resample_poly(data, ratio.numerator, ratio.denominator)


def waveform_to_examples(data: np.ndarray, sample_rate: int,
                         target_sr: Optional[int] = None) -> np.ndarray:
    """Waveform → (num_examples, 96, 64) float32 log-mel patches
    (reference vggish_input.py:26-74 semantics: mono-mean, resample to
    16 kHz, 0.96 s non-overlapping windows, tails dropped)."""
    if data.ndim > 1:
        data = data.mean(axis=1)
    target_sr = target_sr or SAMPLE_RATE
    if sample_rate != target_sr:
        data = resample(data, sample_rate, target_sr)
    log_mel = log_mel_spectrogram(data, target_sr)
    feats_rate = 1.0 / STFT_HOP_SECS
    window = int(round(EXAMPLE_WINDOW_SECS * feats_rate))
    hop = int(round(EXAMPLE_HOP_SECS * feats_rate))
    return frame(log_mel, window, hop).astype(np.float32)
