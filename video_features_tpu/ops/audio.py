"""Audio DSP: waveform → log-mel examples for VGGish.

Numerics re-implementation of the chain behind the reference's
preprocessing (reference models/vggish/vggish_src/mel_features.py 223 LoC,
vggish_input.py 89 LoC): strided framing with floor-truncated tails,
periodic Hann window, magnitude rFFT at the next power of two, an HTK
triangular mel filterbank with a zeroed DC bin, log with offset 0.01, and
0.96 s non-overlapping 96×64 examples.

This runs on the host (float64, exactly like the reference's numpy) — the
DSP is microseconds per clip; the VGG net is the device work. One
divergence: the reference resamples with ``resampy`` (Kaiser polyphase);
here non-16 kHz input is resampled with scipy's polyphase resampler
(`scipy.signal.resample_poly`) — same class of filter, not bit-identical.
Feeding 16 kHz wavs (e.g. asking ffmpeg for ``-ar 16000``) avoids any
resampling difference entirely.
"""
from __future__ import annotations

from fractions import Fraction
from typing import Optional

import numpy as np

SAMPLE_RATE = 16000
STFT_WINDOW_SECS = 0.025
STFT_HOP_SECS = 0.010
NUM_MEL_BINS = 64
MEL_MIN_HZ = 125.0
MEL_MAX_HZ = 7500.0
LOG_OFFSET = 0.01
EXAMPLE_WINDOW_SECS = 0.96
EXAMPLE_HOP_SECS = 0.96

_MEL_BREAK_HZ = 700.0
_MEL_HIGH_Q = 1127.0


def frame(data: np.ndarray, window_length: int, hop_length: int) -> np.ndarray:
    """(T, ...) → (num_frames, window_length, ...); incomplete tails dropped."""
    num_frames = 1 + int(np.floor((data.shape[0] - window_length) / hop_length))
    shape = (num_frames, window_length) + data.shape[1:]
    strides = (data.strides[0] * hop_length,) + data.strides
    return np.lib.stride_tricks.as_strided(data, shape=shape, strides=strides)


def periodic_hann(window_length: int) -> np.ndarray:
    """Full-cycle (period-N) raised cosine — NOT numpy's symmetric hanning."""
    return 0.5 - 0.5 * np.cos(2 * np.pi / window_length
                              * np.arange(window_length))


def stft_magnitude(signal: np.ndarray, fft_length: int, hop_length: int,
                   window_length: int) -> np.ndarray:
    frames = frame(signal, window_length, hop_length)
    return np.abs(np.fft.rfft(frames * periodic_hann(window_length),
                              int(fft_length)))


def hertz_to_mel(frequencies_hertz):
    return _MEL_HIGH_Q * np.log(1.0 + np.asarray(frequencies_hertz)
                                / _MEL_BREAK_HZ)


def mel_matrix(num_mel_bins: int = NUM_MEL_BINS,
               num_spectrogram_bins: int = 257,
               audio_sample_rate: float = SAMPLE_RATE,
               lower_edge_hertz: float = MEL_MIN_HZ,
               upper_edge_hertz: float = MEL_MAX_HZ) -> np.ndarray:
    """(num_spectrogram_bins, num_mel_bins) triangular HTK filterbank,
    linear in mel space, DC bin zeroed."""
    nyquist = audio_sample_rate / 2.0
    if not 0.0 <= lower_edge_hertz < upper_edge_hertz <= nyquist:
        raise ValueError('bad mel band edges')
    spec_mel = hertz_to_mel(np.linspace(0.0, nyquist, num_spectrogram_bins))
    edges = np.linspace(hertz_to_mel(lower_edge_hertz),
                        hertz_to_mel(upper_edge_hertz), num_mel_bins + 2)
    lower = (spec_mel[:, None] - edges[None, :-2]) / (edges[1:-1] - edges[:-2])
    upper = (edges[None, 2:] - spec_mel[:, None]) / (edges[2:] - edges[1:-1])
    weights = np.maximum(0.0, np.minimum(lower, upper))
    weights[0, :] = 0.0
    return weights


def log_mel_spectrogram(data: np.ndarray,
                        audio_sample_rate: float = SAMPLE_RATE) -> np.ndarray:
    window_length = int(round(audio_sample_rate * STFT_WINDOW_SECS))
    hop_length = int(round(audio_sample_rate * STFT_HOP_SECS))
    fft_length = 2 ** int(np.ceil(np.log(window_length) / np.log(2.0)))
    spec = stft_magnitude(data, fft_length, hop_length, window_length)
    mel = spec @ mel_matrix(num_spectrogram_bins=spec.shape[1],
                            audio_sample_rate=audio_sample_rate)
    return np.log(mel + LOG_OFFSET)


def resample(data: np.ndarray, sr: int, target_sr: int = SAMPLE_RATE) -> np.ndarray:
    from scipy.signal import resample_poly
    ratio = Fraction(target_sr, sr)
    return resample_poly(data, ratio.numerator, ratio.denominator)


def waveform_to_examples(data: np.ndarray, sample_rate: int,
                         target_sr: Optional[int] = None) -> np.ndarray:
    """Waveform → (num_examples, 96, 64) float32 log-mel patches
    (reference vggish_input.py:26-74 semantics: mono-mean, resample to
    16 kHz, 0.96 s non-overlapping windows, tails dropped)."""
    if data.ndim > 1:
        data = data.mean(axis=1)
    target_sr = target_sr or SAMPLE_RATE
    if sample_rate != target_sr:
        data = resample(data, sample_rate, target_sr)
    log_mel = log_mel_spectrogram(data, target_sr)
    feats_rate = 1.0 / STFT_HOP_SECS
    window = int(round(EXAMPLE_WINDOW_SECS * feats_rate))
    hop = int(round(EXAMPLE_HOP_SECS * feats_rate))
    return frame(log_mel, window, hop).astype(np.float32)
