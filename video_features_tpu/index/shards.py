"""Mesh-shardable embedding shards + the append-only row manifest.

One :class:`IndexStore` owns one index directory:

    <index_dir>/
      manifest.jsonl                  # add / del / cursor op log
      shards/<family>_<dim>/shard_00000.npy   # (rows<=shard_rows, dim) f32

Vectors live in the shard ``.npy`` files (unpadded, row-major,
L2-normalized by the ingest path so scores are cosine similarities);
*identity* lives in the manifest: one ``add`` record per row mapping
(shard, row) -> (video name, video content hash, window t_ms, cache
key). A ``del`` record tombstones every row of one cache key — the
delete-on-evict coherence hook cache GC fires through
``FeatureCache.on_evict`` — and a ``cursor`` record persists how far
the ingest worker has tailed its source (a byte offset into the cache
manifest), so a restart resumes instead of re-reading.

Shard files are bounded (``shard_rows``) and rewritten atomically on
append (tmp + rename, same discipline as every other artifact in the
tree); ``compact()`` drops tombstoned rows from both the shard files
and the manifest in one atomic pass. Replay is torn-tail tolerant and
self-healing: a manifest row pointing past the end of a (crashed,
short) shard file is dropped, never served.

Everything here is numpy + stdlib — importing the store must not pull
jax (the offline GC tool and the ingest thread never trace a program).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from video_features_tpu.utils.output import (
    CorruptOutputError, atomic_write, load_numpy,
)

# manifest schema version; bump on incompatible record changes
MANIFEST_VERSION = 1

_GroupKey = Tuple[str, int]          # (family, dim)


def _l2_normalize(vectors: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    """Row-wise L2 normalization (float32): with both the indexed rows
    and the query normalized, the query program's matmul scores ARE
    cosine similarities, and a vector's own row is its argmax — the
    property the recall self-check and query-by-video acceptance pin."""
    v = np.asarray(vectors, dtype=np.float32)
    if v.ndim != 2:
        raise ValueError(f'expected (rows, dim) vectors, got {v.shape}')
    norms = np.linalg.norm(v, axis=1, keepdims=True)
    return v / np.maximum(norms, eps)


class _Group:
    """One (family, dim) shard group: parallel vector/meta storage."""

    __slots__ = ('family', 'dim', 'shards', 'metas')

    def __init__(self, family: str, dim: int) -> None:
        self.family = family
        self.dim = dim
        # shards[i] is a (rows_i, dim) float32 array; metas[i][j] is the
        # row's identity dict, or None once tombstoned
        self.shards: List[np.ndarray] = []
        self.metas: List[List[Optional[Dict[str, Any]]]] = []

    def rows_live(self) -> int:
        return sum(1 for rows in self.metas for m in rows if m is not None)

    def rows_dead(self) -> int:
        return sum(1 for rows in self.metas for m in rows if m is None)


class IndexStore:
    """Embedding shards + row manifest for one index directory.

    Thread-safe (one RLock — ingest appends, queries read, GC compacts);
    process-global via :meth:`get` so the serve daemon and its loopback
    commands share one in-memory view, mirroring ``FeatureCache.get``.
    """

    _instances: Dict[str, 'IndexStore'] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get(cls, index_dir: str, shard_rows: int = 1024) -> 'IndexStore':
        index_dir = os.path.abspath(os.path.expanduser(index_dir))
        with cls._instances_lock:
            inst = cls._instances.get(index_dir)
            if inst is None:
                inst = cls(index_dir, shard_rows=shard_rows)
                cls._instances[index_dir] = inst
            return inst

    def __init__(self, index_dir: str, shard_rows: int = 1024) -> None:
        if shard_rows < 1:
            raise ValueError(f'shard_rows must be >= 1, got {shard_rows}')
        self.index_dir = os.path.abspath(os.path.expanduser(index_dir))
        self.shard_rows = int(shard_rows)
        self._lock = threading.RLock()
        self._groups: Dict[_GroupKey, _Group] = {}
        # cache key -> [(gkey, shard_i, row_j)] for delete-on-evict
        self._rows_by_key: Dict[str, List[Tuple[_GroupKey, int, int]]] = {}
        self._cursors: Dict[str, int] = {}
        self.rows_added = 0
        self.rows_dropped = 0
        os.makedirs(os.path.join(self.index_dir, 'shards'), exist_ok=True)
        self._load_manifest()

    # -- paths ---------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.index_dir, 'manifest.jsonl')

    def _group_dir(self, gkey: _GroupKey) -> str:
        family, dim = gkey
        return os.path.join(self.index_dir, 'shards', f'{family}_{dim}')

    def _shard_path(self, gkey: _GroupKey, shard_i: int) -> str:
        return os.path.join(self._group_dir(gkey), f'shard_{shard_i:05d}.npy')

    # -- manifest ------------------------------------------------------------

    def _load_manifest(self) -> None:
        """Replay the op log, then load shard arrays from disk. A torn
        tail (crashed writer) stops the replay at the last whole line; a
        row whose shard file is missing or shorter than its row index is
        dropped (the vectors are the ground truth — identity without a
        vector is unservable either way)."""
        adds: Dict[_GroupKey, Dict[int, Dict[int, Dict[str, Any]]]] = {}
        try:
            with open(self.manifest_path, 'rb') as f:
                raw = f.read()
        except FileNotFoundError:
            return
        for line in raw.split(b'\n'):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                op = rec['op']
            except (ValueError, KeyError, UnicodeDecodeError):
                continue                 # torn/foreign line: skip, keep going
            if op == 'add':
                try:
                    gkey = (str(rec['family']), int(rec['dim']))
                    shard_i, row_j = int(rec['shard']), int(rec['row'])
                    meta = {'video': rec.get('video'),
                            'video_sha256': rec.get('video_sha256'),
                            't_ms': rec.get('t_ms'),
                            'key': rec['key']}
                except (KeyError, TypeError, ValueError):
                    continue
                adds.setdefault(gkey, {}).setdefault(
                    shard_i, {})[row_j] = meta
            elif op == 'del':
                key = rec.get('key')
                for gkey, shards in adds.items():
                    for rows in shards.values():
                        for row_j, meta in list(rows.items()):
                            if meta is not None and meta['key'] == key:
                                rows[row_j] = None
            elif op == 'cursor':
                try:
                    self._cursors[str(rec['source'])] = int(rec['offset'])
                except (KeyError, TypeError, ValueError):
                    continue
        for gkey, shards in sorted(adds.items()):
            group = _Group(*gkey)
            for shard_i in sorted(shards):
                rows = shards[shard_i]
                n_rows = max(rows) + 1 if rows else 0
                try:
                    arr = load_numpy(self._shard_path(gkey, shard_i))
                    arr = np.asarray(arr, dtype=np.float32)
                    if arr.ndim != 2 or arr.shape[1] != gkey[1]:
                        raise CorruptOutputError(
                            f'shard shape {arr.shape} != (*, {gkey[1]})')
                except (OSError, CorruptOutputError, ValueError):
                    arr = np.zeros((0, gkey[1]), dtype=np.float32)
                if arr.shape[0] < n_rows:
                    # crashed mid-publish: manifest rows past the file's
                    # end never got their vectors — drop them
                    for row_j in list(rows):
                        if row_j >= arr.shape[0]:
                            rows[row_j] = None
                    n_rows = arr.shape[0]
                metas: List[Optional[Dict[str, Any]]] = [None] * n_rows
                for row_j, meta in rows.items():
                    if row_j < n_rows:
                        metas[row_j] = meta
                while len(group.shards) < shard_i:
                    # a gap (older shard fully compacted away under a
                    # manifest that still numbers later ones): keep
                    # indices aligned with an empty placeholder
                    group.shards.append(
                        np.zeros((0, gkey[1]), dtype=np.float32))
                    group.metas.append([])
                group.shards.append(arr[:n_rows] if n_rows else
                                    np.zeros((0, gkey[1]), dtype=np.float32))
                group.metas.append(metas)
            self._groups[gkey] = group
        self._reindex_keys_locked()

    def _reindex_keys_locked(self) -> None:
        self._rows_by_key = {}
        for gkey, group in self._groups.items():
            for shard_i, metas in enumerate(group.metas):
                for row_j, meta in enumerate(metas):
                    if meta is not None:
                        self._rows_by_key.setdefault(meta['key'], []).append(
                            (gkey, shard_i, row_j))

    def _append(self, recs: Iterable[Dict[str, Any]]) -> None:
        payload = ''.join(json.dumps(r, sort_keys=True) + '\n' for r in recs)
        if not payload:
            return
        with open(self.manifest_path, 'a', encoding='utf-8') as f:
            f.write(payload)
            f.flush()

    def _rewrite_manifest_locked(self) -> None:
        """Atomic one-line-per-live-row manifest (plus cursors)."""
        recs: List[Dict[str, Any]] = []
        for gkey, group in sorted(self._groups.items()):
            for shard_i, metas in enumerate(group.metas):
                for row_j, meta in enumerate(metas):
                    if meta is not None:
                        recs.append({'op': 'add', 'family': gkey[0],
                                     'dim': gkey[1], 'shard': shard_i,
                                     'row': row_j, **meta})
        for source, offset in sorted(self._cursors.items()):
            recs.append({'op': 'cursor', 'source': source, 'offset': offset})

        def _write(f):
            for r in recs:
                f.write((json.dumps(r, sort_keys=True) + '\n')
                        .encode('utf-8'))

        atomic_write(self.manifest_path, _write)

    def _write_shard_locked(self, gkey: _GroupKey, shard_i: int) -> None:
        os.makedirs(self._group_dir(gkey), exist_ok=True)
        arr = self._groups[gkey].shards[shard_i]
        atomic_write(self._shard_path(gkey, shard_i),
                     lambda f: np.save(f, arr, allow_pickle=False))

    # -- writes --------------------------------------------------------------

    def add_rows(self, family: str, vectors: np.ndarray,
                 metas: List[Dict[str, Any]]) -> int:
        """Fold ``vectors`` (one per meta; normalized here) into the
        (family, dim) group, appending to the tail shard until it hits
        ``shard_rows`` and opening a new one after. Each meta needs at
        least ``key`` (the backing cache key); ``video`` /
        ``video_sha256`` / ``t_ms`` ride along as the search result's
        identity. Returns rows added. Re-adding a cache key already
        live in the index is the ingest replay case: dropped here so
        cursor resets stay idempotent."""
        vectors = _l2_normalize(vectors)
        if len(metas) != vectors.shape[0]:
            raise ValueError(f'{vectors.shape[0]} vectors for '
                             f'{len(metas)} metas')
        if not len(metas):
            return 0
        dim = int(vectors.shape[1])
        gkey = (str(family), dim)
        with self._lock:
            keys = {m['key'] for m in metas}
            live = {k for k in keys if any(
                loc[0] == gkey for loc in self._rows_by_key.get(k, ()))}
            take = [i for i, m in enumerate(metas) if m['key'] not in live]
            if not take:
                return 0
            group = self._groups.get(gkey)
            if group is None:
                group = self._groups.setdefault(gkey, _Group(family, dim))
            recs: List[Dict[str, Any]] = []
            touched: List[int] = []
            for i in take:
                if (not group.shards
                        or group.shards[-1].shape[0] >= self.shard_rows):
                    group.shards.append(np.zeros((0, dim), dtype=np.float32))
                    group.metas.append([])
                shard_i = len(group.shards) - 1
                row_j = group.shards[shard_i].shape[0]
                group.shards[shard_i] = np.concatenate(
                    [group.shards[shard_i], vectors[i:i + 1]], axis=0)
                meta = {'video': metas[i].get('video'),
                        'video_sha256': metas[i].get('video_sha256'),
                        't_ms': metas[i].get('t_ms'),
                        'key': metas[i]['key']}
                group.metas[shard_i].append(meta)
                self._rows_by_key.setdefault(meta['key'], []).append(
                    (gkey, shard_i, row_j))
                recs.append({'op': 'add', 'family': family, 'dim': dim,
                             'shard': shard_i, 'row': row_j, **meta})
                if shard_i not in touched:
                    touched.append(shard_i)
            # vectors first, then identity: replay drops manifest rows
            # the shard file doesn't back, never the other way around
            for shard_i in touched:
                self._write_shard_locked(gkey, shard_i)
            self._append(recs)
            self.rows_added += len(take)
            return len(take)

    def drop_key(self, key: str) -> int:
        """Tombstone every row backed by ``key`` (the delete-on-evict
        hook). Idempotent; returns rows dropped."""
        with self._lock:
            locs = self._rows_by_key.pop(key, None)
            if not locs:
                return 0
            for gkey, shard_i, row_j in locs:
                self._groups[gkey].metas[shard_i][row_j] = None
            self._append([{'op': 'del', 'key': key}])
            self.rows_dropped += len(locs)
            return len(locs)

    def has_key(self, key: str) -> bool:
        with self._lock:
            return bool(self._rows_by_key.get(key))

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._rows_by_key)

    # -- cursors -------------------------------------------------------------

    def cursor(self, source: str) -> int:
        with self._lock:
            return self._cursors.get(source, 0)

    def set_cursor(self, source: str, offset: int) -> None:
        with self._lock:
            self._cursors[str(source)] = int(offset)
            self._append([{'op': 'cursor', 'source': str(source),
                           'offset': int(offset)}])

    # -- reads (query path) --------------------------------------------------

    def families(self) -> List[str]:
        with self._lock:
            return sorted({gkey[0] for gkey in self._groups})

    def group_for(self, family: str,
                  dim: Optional[int] = None) -> Optional[_GroupKey]:
        """Resolve (family, dim); with ``dim`` None the family must map
        to exactly one dim (the common case — one extractor geometry)."""
        with self._lock:
            dims = sorted(g[1] for g in self._groups if g[0] == family)
        if dim is not None:
            return (family, int(dim)) if (family, int(dim)) in self._groups \
                else None
        if len(dims) == 1:
            return (family, dims[0])
        return None

    def shard_views(self, gkey: _GroupKey) -> List[
            Tuple[np.ndarray, np.ndarray, List[Optional[Dict[str, Any]]]]]:
        """Per-shard (vectors, alive_mask float32, metas) snapshots for
        the query program; arrays are copies, safe outside the lock."""
        with self._lock:
            group = self._groups.get(gkey)
            if group is None:
                return []
            out = []
            for arr, metas in zip(group.shards, group.metas):
                mask = np.array([1.0 if m is not None else 0.0
                                 for m in metas], dtype=np.float32)
                out.append((arr.copy(), mask, list(metas)))
            return out

    def rows_for(self, family: str,
                 video_sha256: str) -> Tuple[np.ndarray,
                                             List[Dict[str, Any]]]:
        """Live (vectors, metas) for one video's rows in one family —
        the query-by-video path reads its query vectors straight from
        the index once ingest has folded the extraction in."""
        with self._lock:
            vecs: List[np.ndarray] = []
            metas: List[Dict[str, Any]] = []
            for gkey, group in self._groups.items():
                if gkey[0] != family:
                    continue
                for arr, rows in zip(group.shards, group.metas):
                    for row_j, meta in enumerate(rows):
                        if (meta is not None
                                and meta.get('video_sha256') == video_sha256):
                            vecs.append(arr[row_j])
                            metas.append(dict(meta))
        if not vecs:
            return np.zeros((0, 0), dtype=np.float32), []
        return np.stack(vecs).astype(np.float32), metas

    # -- maintenance ---------------------------------------------------------

    def compact(self) -> Dict[str, Any]:
        """Drop tombstoned rows from shard files, renumber, and rewrite
        the manifest to one line per live row. Safe to run against a
        live index: everything happens under the store lock with atomic
        file replacement."""
        with self._lock:
            rows_dropped = 0
            shards_before = shards_after = 0
            for gkey in sorted(self._groups):
                group = self._groups[gkey]
                shards_before += len(group.shards)
                pairs = [(arr[row_j], meta)
                         for arr, rows in zip(group.shards, group.metas)
                         for row_j, meta in enumerate(rows)]
                live = [(v, m) for v, m in pairs if m is not None]
                rows_dropped += len(pairs) - len(live)
                old_n = len(group.shards)
                group.shards, group.metas = [], []
                for i in range(0, len(live), self.shard_rows):
                    chunk = live[i:i + self.shard_rows]
                    group.shards.append(
                        np.stack([v for v, _ in chunk]).astype(np.float32))
                    group.metas.append([m for _, m in chunk])
                shards_after += len(group.shards)
                for shard_i in range(len(group.shards)):
                    self._write_shard_locked(gkey, shard_i)
                for shard_i in range(len(group.shards), old_n):
                    try:
                        os.remove(self._shard_path(gkey, shard_i))
                    except OSError:
                        pass
            for gkey in [g for g, grp in self._groups.items()
                         if not grp.shards]:
                del self._groups[gkey]
            self._reindex_keys_locked()
            self._rewrite_manifest_locked()
            return {'rows_dropped': int(rows_dropped),
                    'shards_before': int(shards_before),
                    'shards_after': int(shards_after),
                    'rows_live': self.stats()['rows_live']}

    def orphan_sweep(self, contains: Callable[[str], bool]) -> int:
        """Drop every row whose backing cache key ``contains`` denies —
        the offline repair for evictions that happened while no ingest
        worker (and so no ``on_evict`` subscriber) was alive. Returns
        rows dropped."""
        dropped = 0
        for key in self.keys():
            try:
                present = bool(contains(key))
            except Exception:
                # vft-lint: ok=swallowed-exception — a probe failure is
                # NOT evidence of eviction; keeping the row is the safe
                # side (the next sweep retries), dropping it loses data
                present = True
            if not present:
                dropped += self.drop_key(key)
        return dropped

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            rows_live = sum(g.rows_live() for g in self._groups.values())
            rows_dead = sum(g.rows_dead() for g in self._groups.values())
            shards = sum(len(g.shards) for g in self._groups.values())
            families = {}
            for (family, dim), group in sorted(self._groups.items()):
                fam = families.setdefault(family, {
                    'dims': [], 'rows_live': 0, 'shards': 0})
                fam['dims'].append(dim)
                fam['rows_live'] += group.rows_live()
                fam['shards'] += len(group.shards)
            return {'dir': self.index_dir,
                    'shard_rows': self.shard_rows,
                    'rows_live': int(rows_live),
                    'rows_dead': int(rows_dead),
                    'shards': int(shards),
                    'rows_added': int(self.rows_added),
                    'rows_dropped': int(self.rows_dropped),
                    'families': families}
