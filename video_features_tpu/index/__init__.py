"""Feature index: sharded exact nearest-neighbor search over every
extracted embedding.

The cache (``cache/``) makes extraction idempotent; the index makes it
*searchable*. An ingest worker tails the cache's append-only manifest
and folds every published framewise feature object into per-(family,
dim) embedding shards (:mod:`.shards` — bounded, atomically rewritten,
delete-on-evict coherent with cache GC via the store's ``on_evict``
seam). Queries run the one packed top-k program in :mod:`.search`
(batched matmul + ``lax.top_k`` over data-sharded shards, pinned in
``PROGRAMS.lock.json`` and served from the persistent executable store
so a warm boot answers its first query compile-free). :mod:`.service`
is the serving surface behind the loopback ``search``/``index_status``
commands and ``POST /v1/search``; ``tools/index_gc.py`` is the offline
maintenance surface and docs/feature_index.md the operator guide.
"""
from video_features_tpu.index.shards import IndexStore  # noqa: F401
