"""The serve-side face of the feature index.

One :class:`IndexService` rides inside one ``ExtractionServer``:

  * an **ingest worker** (daemon thread, its own watchdog row) tails
    the content-addressed cache's manifest by byte offset and folds
    every published framewise feature object into the
    :class:`~video_features_tpu.index.shards.IndexStore` — normalized
    vectors plus (video, content hash, t_ms, cache key) identity. The
    cursor persists in the index manifest, so a restart resumes; a
    cache-manifest compaction (file shrank) resets it to zero and the
    store's key-dedupe makes the replay idempotent;
  * **delete-on-evict coherence**: the service subscribes to the
    cache's ``on_evict`` seam, so a row whose backing object was
    LRU-evicted (or corrupt-evicted) is tombstoned before the next
    query can return it;
  * the **query surface** behind the loopback ``search`` /
    ``index_status`` commands and ``POST /v1/search``: query-by-vector
    runs the packed top-k program directly; query-by-video extracts
    through the server's own (fused) submit path, waits for ingest to
    fold the result in, then queries with the video's own window
    embeddings.

Telemetry follows the house pattern: ``vft_index_*`` instruments on
the server's registry, an ``index`` section in the metrics document
(mirrored to gauges), ``index_ingest`` / ``index_query`` spans in the
merged trace, and an ``index`` section in the run manifest.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from video_features_tpu.index.search import QueryEngine
from video_features_tpu.index.shards import IndexStore
from video_features_tpu.obs.events import event
from video_features_tpu.utils.output import CorruptOutputError, load_numpy

# the ingest cursor's source id in the index manifest
CURSOR_SOURCE = 'cache_manifest'
# watchdog ledger row for the ingest worker
INGEST_WORKER = 'index-ingest'

# how long search_by_video waits for extraction + ingest to converge
# before answering with whatever is indexed (callers can override)
DEFAULT_SEARCH_TIMEOUT_S = 120.0


def fold_put(store: IndexStore, cache, key: str,
             rec: Dict[str, Any]) -> 'tuple[int, int]':
    """Fold one published cache entry into the index; returns
    ``(rows_added, objects_skipped)``. Entries without the framewise
    object pair (``<family>.npy`` + ``timestamps_ms.npy``) — packed
    multi-stream families, foreign writers — are skipped, not errors.
    Shared by the serve-side ingest worker and the offline ``index``
    CLI so both fold the SAME record semantics."""
    if store.has_key(key):
        return 0, 0
    meta = rec.get('meta') or {}
    family = meta.get('feature_type')
    files = rec.get('files') or {}
    feat = files.get(family) or {}
    ts = files.get('timestamps_ms') or {}
    if not family or not feat.get('name') or not ts.get('name'):
        return 0, 1
    edir = cache._entry_dir(key)        # same internal seam as gc tools
    try:
        vectors = load_numpy(os.path.join(edir, feat['name']))
        t_ms = load_numpy(os.path.join(edir, ts['name']))
    except (OSError, CorruptOutputError, ValueError):
        # evicted/corrupt between manifest append and this read: the
        # del record (or on_evict) owns the cleanup
        return 0, 1
    vectors = np.asarray(vectors)
    t_ms = np.asarray(t_ms).reshape(-1)
    if vectors.ndim != 2 or vectors.shape[0] != t_ms.shape[0] \
            or not vectors.shape[0]:
        return 0, 1
    metas = [{'video': meta.get('video'),
              'video_sha256': meta.get('video_sha256'),
              't_ms': int(t), 'key': key} for t in t_ms]
    return store.add_rows(family, vectors, metas), 0


def fold_manifest(store: IndexStore, cache) -> Dict[str, int]:
    """One offline ingest pass: fold every COMPLETE cache-manifest
    record past the persisted cursor and advance it — the
    ``python -m video_features_tpu index --ingest`` path. Same cursor /
    replay semantics as the serve-side worker (a shrunken source means
    the cache compacted: replay from zero, key-dedupe keeps it
    idempotent)."""
    report = {'rows_added': 0, 'rows_dropped': 0, 'objects_skipped': 0,
              'bytes_folded': 0}
    try:
        size = os.path.getsize(cache.manifest_path)
    except OSError:
        size = 0
    cur = store.cursor(CURSOR_SOURCE)
    if size < cur:
        cur = 0
    if size <= cur:
        return report
    with open(cache.manifest_path, 'rb') as f:
        f.seek(cur)
        data = f.read(size - cur)
    last_nl = data.rfind(b'\n')
    if last_nl < 0:
        return report
    chunk = data[:last_nl + 1]
    for line in chunk.split(b'\n'):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except (ValueError, UnicodeDecodeError):
            continue                     # foreign/torn line: skip
        op, key = rec.get('op'), rec.get('key')
        if not key:
            continue
        if op == 'put':
            added, skipped = fold_put(store, cache, key, rec)
            report['rows_added'] += added
            report['objects_skipped'] += skipped
        elif op == 'del':
            report['rows_dropped'] += store.drop_key(key)
    store.set_cursor(CURSOR_SOURCE, cur + len(chunk))
    report['bytes_folded'] = len(chunk)
    return report


def resolve_index_dir(overrides: Dict[str, Any]) -> str:
    """``index_dir`` knob, else ``<cache_dir>/index`` — beside the
    objects the rows point into (NOT under ``objects/``, so cache GC's
    orphan sweep never touches it)."""
    index_dir = overrides.get('index_dir')
    if not index_dir:
        index_dir = os.path.join(str(overrides.get('cache_dir')), 'index')
    return os.path.abspath(os.path.expanduser(str(index_dir)))


class IndexService:
    """Ingest worker + query engine + stats for one serve process."""

    def __init__(self, server, overrides: Dict[str, Any]) -> None:
        self.server = server
        self.overrides = overrides
        self.poll_s = float(overrides.get('index_poll_s', 0.5))
        self.store = IndexStore.get(
            resolve_index_dir(overrides),
            shard_rows=int(overrides.get('index_shard_rows', 1024)))
        from video_features_tpu.cache.store import FeatureCache
        cache_l2 = overrides.get('cache_l2_dir')
        if cache_l2:
            # fleet tier: ingest tails the LOCAL manifest as before, but
            # fetches of rows a peer published resolve through the L2
            from video_features_tpu.fleet.tier import TieredFeatureCache
            self.cache = TieredFeatureCache.get_pair(
                overrides.get('cache_dir'), cache_l2,
                overrides.get('cache_max_bytes'))
        else:
            self.cache = FeatureCache.get(overrides.get('cache_dir'),
                                          overrides.get('cache_max_bytes'))
        aot_store = None
        if overrides.get('aot_enabled'):
            from video_features_tpu.aot import ExecStore, log_aot_error
            aot_l2 = overrides.get('aot_l2_dir')
            try:
                if aot_l2:
                    from video_features_tpu.fleet.artifacts import (
                        TieredExecStore,
                    )
                    aot_store = TieredExecStore.get_pair(
                        overrides.get('aot_dir'), aot_l2,
                        overrides.get('aot_max_bytes'))
                else:
                    aot_store = ExecStore.get(overrides.get('aot_dir'),
                                              overrides.get('aot_max_bytes'))
            except Exception:
                log_aot_error(f'open ({overrides.get("aot_dir")})')
        self.engine = QueryEngine(
            self.store, aot_store=aot_store,
            query_block=int(overrides.get('index_query_block', 8)),
            k_max=int(overrides.get('index_k_max', 10)))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.ingest_lag_bytes = 0
        self.objects_skipped = 0
        self.ingest_errors = 0
        reg = server.registry
        self._c_rows = reg.counter(
            'vft_index_rows_indexed_total',
            'embedding rows folded into the feature index')
        self._c_dropped = reg.counter(
            'vft_index_rows_dropped_total',
            'index rows tombstoned (cache eviction / del replay)')
        self._c_queries = reg.counter(
            'vft_index_queries_total', 'index query vectors served')
        self._h_query = reg.histogram(
            'vft_index_query_latency_seconds',
            'index search latency (admission to merged hits)')
        self._g_lag = reg.gauge(
            'vft_index_ingest_lag_bytes',
            'cache-manifest bytes the ingest worker has not folded yet')
        self._recorder = None
        if server.base_overrides.get('trace_out'):
            # index spans join the server-wide merged Perfetto export —
            # persistent, like the ingress recorder: pool churn must not
            # age out the ingest worker's lane
            from video_features_tpu.obs.spans import SpanRecorder
            self._recorder = SpanRecorder()
            server._persistent_recorders.append(self._recorder)
        # delete-on-evict coherence: fires AFTER the cache lock is
        # released (store queues notices, drains outside the lock), so
        # the callback may safely re-enter the cache — but it still
        # stays cheap (tombstone + one manifest line)
        self.cache.on_evict.append(self._on_cache_evict)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> 'IndexService':
        self._thread = threading.Thread(
            target=self._ingest_loop, name=INGEST_WORKER, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
        wd = getattr(self.server, 'watchdog', None)
        if wd is not None:
            wd.forget(INGEST_WORKER)
        try:
            self.cache.on_evict.remove(self._on_cache_evict)
        except ValueError:
            pass

    def prewarm(self) -> str:
        """Make the canonical top-k executable resident before the
        first query (the ``serve_prewarm: [index]`` path)."""
        path = self.engine.prewarm()
        event(logging.INFO, f'index query program {path}',
              subsystem='index', program='topk', path=path)
        return path

    # -- eviction coherence --------------------------------------------------

    def _on_cache_evict(self, key: str, corrupt: bool) -> None:
        dropped = self.store.drop_key(key)
        if dropped:
            self._c_dropped.inc(dropped)

    # -- ingest --------------------------------------------------------------

    def _ingest_loop(self) -> None:
        wd = getattr(self.server, 'watchdog', None)
        if wd is not None:
            wd.advance(INGEST_WORKER, 'index_ingest')
        while not self._stop.is_set():
            try:
                progressed = self._ingest_once()
            except Exception:
                with self._lock:
                    self.ingest_errors += 1
                event(logging.WARNING, 'index ingest cycle failed',
                      subsystem='index', exc_info=True)
                progressed = False
            self._stop.wait(0.01 if progressed else self.poll_s)

    def _ingest_once(self) -> bool:
        """Fold one batch of cache-manifest records; True if any byte
        of the source was consumed (caller polls faster while behind)."""
        wd = getattr(self.server, 'watchdog', None)
        try:
            size = os.path.getsize(self.cache.manifest_path)
        except OSError:
            size = 0
        cur = self.store.cursor(CURSOR_SOURCE)
        if size < cur:
            # the cache compacted its manifest under us: replay from the
            # top — add_rows dedupes by cache key, del is idempotent
            cur = 0
        lag = max(0, size - cur)
        with self._lock:
            self.ingest_lag_bytes = lag
        self._g_lag.set(lag)
        if wd is not None:
            wd.set_pending(INGEST_WORKER, 1 if lag else 0)
        if not lag:
            return False
        t0 = time.perf_counter()
        with open(self.cache.manifest_path, 'rb') as f:
            f.seek(cur)
            data = f.read(size - cur)
        last_nl = data.rfind(b'\n')
        if last_nl < 0:
            return False                 # torn tail only: wait for more
        chunk = data[:last_nl + 1]
        rows = 0
        for line in chunk.split(b'\n'):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except (ValueError, UnicodeDecodeError):
                continue                 # foreign/torn line: skip
            op, key = rec.get('op'), rec.get('key')
            if not key:
                continue
            if op == 'put':
                added, skipped = fold_put(self.store, self.cache, key, rec)
                rows += added
                if added:
                    self._c_rows.inc(added)
                if skipped:
                    with self._lock:
                        self.objects_skipped += skipped
            elif op == 'del':
                dropped = self.store.drop_key(key)
                if dropped:
                    self._c_dropped.inc(dropped)
        new_cur = cur + len(chunk)
        self.store.set_cursor(CURSOR_SOURCE, new_cur)
        lag = max(0, size - new_cur)
        with self._lock:
            self.ingest_lag_bytes = lag
        self._g_lag.set(lag)
        if wd is not None:
            wd.advance(INGEST_WORKER, 'index_ingest')
            wd.set_pending(INGEST_WORKER, 1 if lag else 0)
        if self._recorder is not None:
            t1 = time.perf_counter()
            self._recorder.span('index_ingest', t0, t1, rows=rows,
                                bytes=len(chunk))
        return True

    # -- queries -------------------------------------------------------------

    def search_vector(self, family: str, vector, k: int = 10,
                      dim: Optional[int] = None) -> Dict[str, Any]:
        t0 = time.perf_counter()
        try:
            queries = np.asarray(vector, dtype=np.float32)
        except (TypeError, ValueError) as e:
            return {'ok': False, 'error': f'malformed query vector: {e}'}
        try:
            hits, _wall = self.engine.search(family, queries, k, dim=dim)
        except ValueError as e:
            return {'ok': False, 'error': str(e)}
        dt = time.perf_counter() - t0
        self._h_query.observe(dt)
        self._c_queries.inc(len(hits))
        if self._recorder is not None:
            self._recorder.span('index_query', t0, t0 + dt, family=family,
                                queries=len(hits), k=k)
        merged = hits[0] if len(hits) == 1 else \
            QueryEngine.merge_hits(hits, k)
        return {'ok': True, 'family': family, 'k': k, 'hits': merged,
                'wall_s': round(dt, 6)}

    def search_by_video(self, video_path: str,
                        features: Optional[List[str]] = None,
                        k: int = 10, timeout_s: Optional[float] = None,
                        priority: str = 'interactive',
                        traceparent: Optional[str] = None,
                        ) -> Dict[str, Any]:
        """Extract ``video_path`` through the server's own (fused)
        submit path, wait for ingest to fold the result in, then query
        each family with the video's own window embeddings."""
        t0 = time.perf_counter()
        deadline = t0 + (DEFAULT_SEARCH_TIMEOUT_S if timeout_s is None
                         else float(timeout_s))
        if not features:
            return {'ok': False,
                    'error': 'search by video requires features: [..]'}
        try:
            from video_features_tpu.cache.key import hash_file
            sha = hash_file(video_path)
        except OSError as e:
            return {'ok': False, 'error': f'unreadable video: {e}'}
        result = self.server.submit(
            None, [video_path], features=list(features),
            priority=priority, traceparent=traceparent)
        if not result.get('ok'):
            return result
        rid = result['request_id']
        while time.perf_counter() < deadline:
            st = self.server.status(rid)
            if st.get('ok') and st.get('state') != 'running':
                break
            time.sleep(0.05)
        results: Dict[str, Any] = {}
        errors: Dict[str, str] = {}
        for family in features:
            qvecs: np.ndarray = np.zeros((0, 0), np.float32)
            while time.perf_counter() < deadline:
                qvecs, _ = self.store.rows_for(family, sha)
                if qvecs.shape[0]:
                    break
                time.sleep(0.05)
            if not qvecs.shape[0]:
                errors[family] = ('no indexed rows for this video '
                                  '(extraction failed or ingest timed out)')
                continue
            tq = time.perf_counter()
            try:
                hits, _wall = self.engine.search(family, qvecs, k)
            except ValueError as e:
                errors[family] = str(e)
                continue
            dt = time.perf_counter() - tq
            self._h_query.observe(dt)
            self._c_queries.inc(len(hits))
            if self._recorder is not None:
                self._recorder.span('index_query', tq, tq + dt,
                                    family=family, queries=len(hits),
                                    k=k, request_id=rid)
            results[family] = QueryEngine.merge_hits(hits, k)
        out: Dict[str, Any] = {
            'ok': bool(results) or not errors,
            'request_id': rid, 'video_sha256': sha, 'k': k,
            'results': results,
            'wall_s': round(time.perf_counter() - t0, 6)}
        if errors:
            out['errors'] = errors
            if not results:
                out['error'] = '; '.join(
                    f'{f}: {e}' for f, e in sorted(errors.items()))
        return out

    # -- telemetry -----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The serve metrics document's ``index`` section (numeric keys
        mirror to ``vft_index_*`` gauges; names are disjoint from the
        registered counter/histogram families above)."""
        s = self.store.stats()
        with self._lock:
            lag = self.ingest_lag_bytes
            skipped = self.objects_skipped
            errors = self.ingest_errors
        return {'enabled': True,
                'dir': s['dir'],
                'rows_live': s['rows_live'],
                'rows_dead': s['rows_dead'],
                'shards': s['shards'],
                'rows_indexed': s['rows_added'],
                'rows_dropped': s['rows_dropped'],
                'ingest_lag_bytes': lag,
                'objects_skipped': skipped,
                'ingest_errors': errors,
                'queries': self.engine.queries_total,
                'programs_loaded': self.engine.programs_loaded,
                'programs_compiled': self.engine.programs_compiled,
                'families': s['families']}
