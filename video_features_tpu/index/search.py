"""The exact top-k query program + its runtime dispatch.

One program serves every query: ``topk(shard, queries, row_mask)`` —
a batched matmul of L2-normalized queries against one data-sharded
shard block, tombstones masked to ``-inf``, then ``lax.top_k``. Exact
search, by construction: recall@k is 1.0 and the bench rung that
reports it is a self-check, not a tuning knob.

The program is a first-class citizen of both contract gates:

  * ``analysis/programs.py`` pins it in PROGRAMS.lock.json under the
    pseudo-family ``index`` at the CANONICAL geometry below, checked at
    mesh widths {1, 2} like every extractor program (no f64, leading
    batch axis divisible by the mesh, const budget);
  * the serve runtime reaches it only through ``aot.ensure_program``,
    so a warm boot loads the persisted executable and answers its
    first query compile-free (``serve_prewarm: [index]``).

Runtime geometries are quantized so the executable store stays small:
every shard is padded to ``shard_rows`` rows (mask 0 on padding) and
queries to ``query_block`` — one executable per (shard_rows, dim,
query_block, k), regardless of corpus size. ``k`` is static (the lock
pins ``K``); callers asking for less get a slice of the top-K.

jax is imported lazily — ``index.shards`` and the offline GC tool must
import without it.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# canonical lock geometry: what PROGRAMS.lock.json pins and what
# serve_prewarm warms. 1024 x 512 is one full shard of clip-sized
# embeddings; 8 queries is the query_block default; K=10 feeds the
# recall@10 bench rung.
INDEX_ROWS = 1024
INDEX_DIM = 512
INDEX_QUERIES = 8
INDEX_K = 10

_jit_lock = threading.Lock()
_jitted = None


def _topk_impl(shard, queries, row_mask, *, k: int):
    import jax
    import jax.numpy as jnp
    # scores are cosine similarities (both sides L2-normalized at
    # ingest/query time); dead + padding rows drop to -inf so they can
    # never crack the top-k
    scores = queries @ shard.T
    scores = jnp.where(row_mask[None, :] > 0, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def topk_jitted():
    """The one jitted query callable (``k`` static) — the SAME object
    feeds the lock check, the AOT store, and the jit fallback, so the
    pinned StableHLO is the lowering of the real dispatch target."""
    global _jitted
    with _jit_lock:
        if _jitted is None:
            import jax
            _jitted = jax.jit(_topk_impl, static_argnames=('k',))
        return _jitted


class IndexPrograms:
    """``program_specs`` provider for the ``index`` pseudo-family —
    the same shape ``analysis/programs.py`` collects from extractors."""

    feature_type = 'index'

    def __init__(self, rows: int = INDEX_ROWS, dim: int = INDEX_DIM,
                 queries: int = INDEX_QUERIES, k: int = INDEX_K) -> None:
        self.rows, self.dim = int(rows), int(dim)
        self.queries, self.k = int(queries), int(k)

    def abstract_args(self, mesh=None) -> Tuple[Any, Any, Any]:
        import jax
        import jax.numpy as jnp

        from video_features_tpu.parallel.mesh import (
            batch_sharding, replicated,
        )
        batch = batch_sharding(mesh) if mesh is not None else None
        repl = replicated(mesh) if mesh is not None else None
        shard = jax.ShapeDtypeStruct((self.rows, self.dim), jnp.float32,
                                     sharding=batch)
        queries = jax.ShapeDtypeStruct((self.queries, self.dim),
                                       jnp.float32, sharding=repl)
        mask = jax.ShapeDtypeStruct((self.rows,), jnp.float32,
                                    sharding=batch)
        return shard, queries, mask

    def program_specs(self, mesh=None) -> List[Any]:
        from video_features_tpu.analysis.programs import ProgramSpec
        return [ProgramSpec('topk', topk_jitted(),
                            self.abstract_args(mesh=mesh),
                            kwargs=dict(k=self.k), batch_argnum=0)]


class QueryEngine:
    """Runtime dispatch: pad to the quantized geometry, run the program
    (AOT-resident when a store is given, jit otherwise), merge per-query
    hits across shards on the host."""

    def __init__(self, store, aot_store=None,
                 query_block: int = INDEX_QUERIES,
                 k_max: int = INDEX_K) -> None:
        self.store = store                      # IndexStore
        self.aot_store = aot_store              # aot.store.ExecStore | None
        self.query_block = max(1, int(query_block))
        self.k_max = max(1, int(k_max))
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[int, int, int, int], Any] = {}
        self.programs_loaded = 0
        self.programs_compiled = 0
        self.queries_total = 0

    # -- program residency ---------------------------------------------------

    def _program(self, rows: int, dim: int, k: int):
        """The resident callable for one (rows, dim, query_block, k)
        geometry; None means 'call the jitted fallback'."""
        if self.aot_store is None:
            return None
        geom = (rows, dim, self.query_block, k)
        with self._lock:
            prog = self._programs.get(geom)
        if prog is not None:
            return prog
        import jax.numpy as jnp

        import jax

        from video_features_tpu.aot.runtime import ensure_program
        args = (jax.ShapeDtypeStruct((rows, dim), jnp.float32),
                jax.ShapeDtypeStruct((self.query_block, dim), jnp.float32),
                jax.ShapeDtypeStruct((rows,), jnp.float32))
        prog, path = ensure_program(
            self.aot_store, f'topk_{rows}x{dim}q{self.query_block}'
            f'k{k}', topk_jitted(), args,
            statics={'k': k}, lane='float32',
            feature_type='index')
        with self._lock:
            self._programs[geom] = prog
            if path == 'loaded':
                self.programs_loaded += 1
            else:
                self.programs_compiled += 1
        return prog

    def prewarm(self, rows: int = INDEX_ROWS, dim: int = INDEX_DIM) -> str:
        """Make the canonical-geometry executable resident (load or
        compile+publish); returns 'loaded' | 'compiled' | 'jit'."""
        if self.aot_store is None:
            topk_jitted()                        # at least build the jit
            return 'jit'
        before = self.programs_loaded
        self._program(rows, dim, self.k_max)
        return 'loaded' if self.programs_loaded > before else 'compiled'

    # -- queries -------------------------------------------------------------

    def _run(self, shard: np.ndarray, queries: np.ndarray,
             mask: np.ndarray, k: int):
        # k is clamped by the caller to the padded row count — top_k
        # cannot ask for more rows than the shard block holds
        prog = self._program(shard.shape[0], shard.shape[1], k)
        if prog is not None:
            values, idx = prog(shard, queries, mask)
        else:
            values, idx = topk_jitted()(shard, queries, mask, k=k)
        return np.asarray(values), np.asarray(idx)

    def search(self, family: str, queries: np.ndarray, k: int,
               dim: Optional[int] = None,
               ) -> Tuple[List[List[Dict[str, Any]]], float]:
        """Exact top-k for each query vector against one family's
        shards. Returns (per-query hit lists, wall seconds); each hit
        is ``{score, video, video_sha256, t_ms, key, family}``. Raises
        ValueError when the family has no (unambiguous) shard group or
        the query dim doesn't match."""
        t0 = time.perf_counter()
        queries = np.asarray(queries, dtype=np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.ndim != 2 or not queries.shape[0]:
            raise ValueError(f'expected (n, dim) queries, '
                             f'got shape {queries.shape}')
        gkey = self.store.group_for(family, dim=dim)
        if gkey is None:
            dims = sorted(g[1] for g in getattr(self.store, '_groups', {})
                          if g[0] == family)
            raise ValueError(
                f'no indexed shards for family {family!r}'
                + (f' (ambiguous dims {dims}; pass dim=)' if len(dims) > 1
                   else ''))
        if queries.shape[1] != gkey[1]:
            raise ValueError(f'query dim {queries.shape[1]} != indexed '
                             f'dim {gkey[1]} for family {family!r}')
        k = max(1, min(int(k), self.k_max))
        rows_pad = max(self.store.shard_rows, 1)
        k_run = min(self.k_max, rows_pad)
        # normalize queries so scores are cosine similarities
        norms = np.linalg.norm(queries, axis=1, keepdims=True)
        queries = queries / np.maximum(norms, 1e-12)

        n_real = queries.shape[0]
        hits: List[List[Dict[str, Any]]] = [[] for _ in range(n_real)]
        views = self.store.shard_views(gkey)
        for q0 in range(0, n_real, self.query_block):
            qblock = queries[q0:q0 + self.query_block]
            q_pad = np.zeros((self.query_block, gkey[1]), dtype=np.float32)
            q_pad[:qblock.shape[0]] = qblock
            for arr, mask, metas in views:
                if arr.shape[0] == 0:
                    continue
                shard_pad = np.zeros((rows_pad, gkey[1]), dtype=np.float32)
                shard_pad[:arr.shape[0]] = arr
                mask_pad = np.zeros((rows_pad,), dtype=np.float32)
                mask_pad[:mask.shape[0]] = mask
                values, idx = self._run(shard_pad, q_pad, mask_pad, k_run)
                for qi in range(qblock.shape[0]):
                    for score, row_j in zip(values[qi], idx[qi]):
                        if not np.isfinite(score):
                            continue
                        meta = metas[row_j] if row_j < len(metas) else None
                        if meta is None:
                            continue
                        hits[q0 + qi].append(
                            {'score': float(score), 'family': family,
                             **meta})
        for lst in hits:
            lst.sort(key=lambda h: -h['score'])
            del lst[k:]
        self.queries_total += n_real
        return hits, time.perf_counter() - t0

    @staticmethod
    def merge_hits(per_query: List[List[Dict[str, Any]]],
                   k: int) -> List[Dict[str, Any]]:
        """Fold per-query hit lists into one ranking: max score per
        distinct (key, t_ms) row — the query-by-video response shape."""
        best: Dict[Tuple[Any, Any], Dict[str, Any]] = {}
        for lst in per_query:
            for h in lst:
                ident = (h.get('key'), h.get('t_ms'))
                if ident not in best or h['score'] > best[ident]['score']:
                    best[ident] = h
        out = sorted(best.values(), key=lambda h: -h['score'])
        return out[:max(1, int(k))]
