"""Offline feature-index surface: ``python -m video_features_tpu index``.

The serve-side ingest worker and ``POST /v1/search`` need a resident
daemon; this entry point needs only the directories. It folds the cache
manifest with the SAME record/cursor semantics (``service.fold_manifest``)
and runs the SAME exact top-k program (``search.QueryEngine``), so an
offline query and a served query over one index answer identically.

Actions compose in one invocation (ingest → compact → query → status):

  python -m video_features_tpu index --cache-dir C --ingest
  python -m video_features_tpu index --cache-dir C \
      --query q.npy --family resnet --k 10
  python -m video_features_tpu index --cache-dir C --status

One JSON report on stdout (machine-parseable, like the gc tools);
``--manifest-out`` additionally writes a run manifest whose ``index``
section carries the same numbers.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List

from video_features_tpu.index.shards import IndexStore


def index_main(argv: List[str]) -> int:
    p = argparse.ArgumentParser(
        prog='python -m video_features_tpu index',
        description='offline feature-index maintenance and queries')
    p.add_argument('--cache-dir', required=True,
                   help='the content-addressed feature cache to index')
    p.add_argument('--index-dir', default=None,
                   help='index location (default: <cache-dir>/index)')
    p.add_argument('--shard-rows', type=int, default=1024,
                   help='rows per embedding shard (index_shard_rows)')
    p.add_argument('--ingest', action='store_true',
                   help='fold new cache-manifest records into the index')
    p.add_argument('--compact', action='store_true',
                   help='rewrite shards without tombstoned rows')
    p.add_argument('--query', default=None, metavar='VEC_NPY',
                   help='.npy query vector (or 2D batch) for exact top-k')
    p.add_argument('--family', default=None,
                   help='feature family to query (required with --query '
                        'when the index holds more than one)')
    p.add_argument('--k', type=int, default=10,
                   help='hits per query (default 10)')
    p.add_argument('--status', action='store_true',
                   help='report index stats (the default action)')
    p.add_argument('--manifest-out', default=None,
                   help='also write a run manifest with an index section')
    args = p.parse_args(argv)

    store = IndexStore.get(
        _index_dir(args.cache_dir, args.index_dir),
        shard_rows=args.shard_rows)
    report: Dict[str, Any] = {'ok': True}

    if args.ingest:
        # a FRESH cache instance: an offline tool reads the disk state
        # as-is, never the (possibly stale) in-process singleton view
        from video_features_tpu.cache.store import FeatureCache
        from video_features_tpu.index.service import fold_manifest
        report['ingest'] = fold_manifest(
            store, FeatureCache(args.cache_dir))
    if args.compact:
        report['compact'] = store.compact()
    if args.query is not None:
        try:
            report['query'] = _run_query(store, args)
        except (OSError, ValueError) as e:
            report['ok'] = False
            report['error'] = str(e)
    report['index'] = store.stats()

    if args.manifest_out:
        from video_features_tpu.obs.manifest import RunManifest
        man = RunManifest({'cache_dir': args.cache_dir})
        man.note_index(report['index'])
        man.write(args.manifest_out)
        report['manifest_out'] = args.manifest_out

    print(json.dumps(report, sort_keys=True), file=sys.stdout)
    return 0 if report['ok'] else 1


def _index_dir(cache_dir: str, index_dir: 'str | None') -> str:
    from video_features_tpu.index.service import resolve_index_dir
    overrides: Dict[str, Any] = {'cache_dir': cache_dir}
    if index_dir:
        overrides['index_dir'] = index_dir
    return resolve_index_dir(overrides)


def _run_query(store: IndexStore, args) -> Dict[str, Any]:
    import numpy as np

    from video_features_tpu.index.search import QueryEngine
    from video_features_tpu.utils.output import load_numpy
    family = args.family
    if family is None:
        families = store.families()
        if len(families) != 1:
            raise ValueError(
                '--family is required: the index holds '
                f'{sorted(families) if families else "no"} families')
        family = next(iter(families))
    queries = np.asarray(load_numpy(args.query), dtype=np.float32)
    engine = QueryEngine(store, aot_store=None)
    per_query, wall_s = engine.search(family, queries, args.k)
    merged = per_query[0] if len(per_query) == 1 \
        else QueryEngine.merge_hits(per_query, args.k)
    return {'family': family, 'k': args.k,
            'queries': int(np.atleast_2d(queries).shape[0]),
            'hits': merged, 'wall_s': round(wall_s, 6)}
