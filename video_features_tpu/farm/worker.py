"""Decode-farm worker process: decode videos, ship windows over SHM.

Spawned (never forked — the parent holds live XLA/jax state that must
not cross a fork) with a picklable recipe (``farm/recipes.py``). The
import footprint is deliberately tiny: numpy, cv2/PIL via ``io.video``,
and the jax-free host transforms — a worker never imports jax, so spawn
cost stays at interpreter + cv2 startup.

Wire protocol (all messages on this worker's own ``out_q``; every
message leads with ``(kind, widx, epoch, ...)`` and stale epochs are
dropped by the consumer after a respawn):

  ('clock', widx, epoch, t_parent0, t_worker)          calibration reply
  ('start', widx, epoch, seq, info)                    video opened
  ('win',   widx, epoch, seq, off, adv, shape, dtype, meta, t0, dt,
            ring_used)
  ('winq',  widx, epoch, seq, bytes, shape, dtype, meta, t0, dt)
                           queue-transport fallback (window > ring/2)
  ('end',   widx, epoch, seq, n_windows)               video drained
  ('err',   widx, epoch, seq, traceback)               video failed

Control (``ctrl_q``, consumer → worker): ('sync', t_parent0) at spawn
opens the clock-calibration handshake (answered with 'clock' above so
the parent can place in-worker decode spans on its own timeline);
('abort', seq) stops decoding
that video early (device-side fault made its windows worthless);
('winq_ack',) credits back one consumed queue-transport window — the
worker holds at most ``MAX_UNACKED_WINQ`` unacked 'winq' messages, so
the oversized-window fallback is as backpressured as the ring (a slow
consumer stalls decode instead of growing the parent's queue);
('stop',) on ``task_q`` ends the process after the queued videos.

Fault model: any exception inside one video's decode is that video's
'err' — the worker moves on (the per-video error contract). A crash
(segfault, OOM-kill) takes the process; the farm supervisor fails the
in-flight video, re-dispatches the queued ones to a respawned worker
with a FRESH ring epoch, and unlinks the dead ring.
"""
from __future__ import annotations

import queue as queue_mod
import time
import traceback


class _Abort(Exception):
    """Current video's windows are no longer wanted."""


# in-flight cap for queue-transport windows (> ring/2, so potentially
# ~100 MiB each): one being consumed + one buffered per worker
MAX_UNACKED_WINQ = 2


def worker_main(widx: int, epoch: int, recipe, ring_name: str,
                ring_bytes: int, task_q, out_q, free_q, ctrl_q) -> None:
    import numpy as np

    from video_features_tpu.ops import host_transforms
    from multiprocessing import shared_memory

    from video_features_tpu.farm.ring import RingProducer

    # NOTE on the resource tracker: attaching registers the segment with
    # the (inherited, shared) tracker a second time — a set, so the
    # parent's unlink on shutdown/respawn still unregisters cleanly. Do
    # NOT unregister here: the tracker would then KeyError on the
    # parent's legitimate unlink.
    shm = shared_memory.SharedMemory(name=ring_name)
    ring = RingProducer(shm.buf, ring_bytes)
    aborted = set()
    winq_unacked = [0]                   # queue-transport credit counter

    # clock-calibration handshake (vft-flight): the parent put
    # ('sync', t_parent0) on ctrl_q right after spawn; answering with
    # our own perf_counter reading lets the parent convert in-worker
    # span timestamps onto ITS clock (midpoint method — the offset
    # error is bounded by half the message round trip), so the merged
    # timeline shows true in-worker decode time under this worker's
    # pid. Best-effort: no sync within the timeout just means
    # uncalibrated (zero-offset) spans.
    try:
        first = ctrl_q.get(timeout=10)
        if first and first[0] == 'sync':
            out_q.put(('clock', widx, epoch, first[1],
                       time.perf_counter()))
        elif first and first[0] == 'winq_ack':
            winq_unacked[0] -= 1
        elif first and first[0] == 'abort':
            aborted.add(first[1])
    except queue_mod.Empty:
        pass

    def poll_ctrl() -> None:
        while True:
            try:
                msg = ctrl_q.get_nowait()
            except queue_mod.Empty:
                return
            if msg[0] == 'abort':
                aborted.add(msg[1])
            elif msg[0] == 'winq_ack':
                winq_unacked[0] -= 1
            elif msg[0] == 'sync':
                # calibration REFINEMENT round trip: the parent re-syncs
                # while we are actively decoding (polling every window),
                # so this exchange is tight — unlike the startup one,
                # whose round trip spans process spawn. The parent keeps
                # the minimum-RTT measurement (farm._handle 'clock').
                out_q.put(('clock', widx, epoch, msg[1],
                           time.perf_counter()))

    def wait_free_for(seq):
        def wait_free():
            poll_ctrl()
            if seq in aborted:
                raise _Abort
            try:
                ring.freed(free_q.get(timeout=0.1))
            except queue_mod.Empty:
                pass
        return wait_free

    def drain_frees() -> None:
        while True:
            try:
                ring.freed(free_q.get_nowait())
            except queue_mod.Empty:
                return

    try:
        while True:
            msg = task_q.get()
            if msg[0] == 'stop':
                break
            # ('video', seq, path[, segment[, select]]) — segment is the
            # optional (start_s, end_s) range of a segment query,
            # replayed by the recipe with the exact frame filter the
            # in-process path uses; select is the fused-worklist family
            # subset (FusedRecipe only): families answered from cache
            # drop out of the shared decode's fan-out
            _, seq, path = msg[:3]
            segment = msg[3] if len(msg) > 3 else None
            select = msg[4] if len(msg) > 4 else None
            n = 0
            try:
                # keywords only when actually set: recipes predating the
                # segment/select contracts keep working for plain tasks
                kw = {}
                if segment is not None:
                    kw['segment'] = segment
                if select is not None:
                    kw['select'] = select
                info, windows = recipe.open(path, **kw)
                out_q.put(('start', widx, epoch, seq, info))
                it = iter(windows)
                wait_free = wait_free_for(seq)
                while True:
                    poll_ctrl()
                    if seq in aborted:
                        if hasattr(it, 'close'):
                            it.close()     # recipe finally → loader.close
                        break
                    t0 = time.perf_counter()
                    try:
                        window, meta = next(it)
                    except StopIteration:
                        break
                    dt = time.perf_counter() - t0
                    window = np.ascontiguousarray(window)
                    if not host_transforms.frames_match_device_contract(
                            window):
                        # uint8-in/uint8-out contract
                        # (ops/host_transforms.py): a float window here
                        # means a transform leaked numpy default-dtype
                        # math — ship NOTHING (the parent's in-process
                        # replay would disagree byte-for-byte); the
                        # 'err' contract fails just this video, loudly
                        raise TypeError(
                            f'recipe produced a {window.dtype} window '
                            f'for {path} — farm windows must be uint8 '
                            f'(host transforms never run float math; '
                            f'see ops/host_transforms.py dtype '
                            f'contract)')
                    drain_frees()
                    region = ring.alloc(window.nbytes, wait_free)
                    if region is None:
                        # window larger than half the ring: correctness
                        # valve — ship the bytes through the queue, but
                        # bounded by consumer acks so a slow consumer
                        # stalls decode here exactly like the ring does
                        while winq_unacked[0] >= MAX_UNACKED_WINQ \
                                and seq not in aborted:
                            poll_ctrl()
                            time.sleep(0.005)
                        if seq in aborted:
                            continue   # loop top closes the iterator
                        winq_unacked[0] += 1
                        out_q.put(('winq', widx, epoch, seq,
                                   window.tobytes(), window.shape,
                                   window.dtype.str, meta, t0, dt))
                    else:
                        off, adv = region
                        ring.write(off, window)
                        out_q.put(('win', widx, epoch, seq, off, adv,
                                   window.shape, window.dtype.str, meta,
                                   t0, dt,
                                   ring.write_pos - ring.read_pos))
                    n += 1
                out_q.put(('end', widx, epoch, seq, n))
            except _Abort:
                out_q.put(('end', widx, epoch, seq, n))
            # vft-lint: ok=swallowed-exception — the 'err' message IS
            # the report: it carries the full traceback to the parent,
            # whose drain loop routes it through obs.events (workers are
            # jax-free spawn processes and keep no logging config)
            except Exception:
                # one video's decode failure is that video's error; the
                # worker stays up for the rest of the worklist
                out_q.put(('err', widx, epoch, seq,
                           traceback.format_exc()))
    finally:
        try:
            shm.close()
        except Exception:
            # vft-lint: ok=swallowed-exception — exit-path close of a
            # segment the parent may already have unlinked
            pass
