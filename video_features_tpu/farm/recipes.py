"""Picklable decode recipes: what a farm worker runs per video.

A recipe is the farm's contract with the extractor families: a small,
picklable description of the decode + host-preprocess stack that a
worker PROCESS can replay with byte-exact parity to the in-process path
— without ever holding (or pickling) the extractor itself, whose device
params and compiled executables must stay in the parent.

``recipe.open(path)`` → ``(info, iterator)`` where ``info`` is the
video-level metadata dict the scheduler folds into ``task.info`` (e.g.
``fps`` for the frame-wise families) and the iterator yields
``(window, meta)`` exactly like ``BaseExtractor.packed_windows``.

Transforms are named specs (``('edge_resize', ...)`` /
``('edge_resize_crop', ...)``) resolved against the jax-free
``ops.host_transforms`` primitives, so workers import cv2/PIL/numpy and
nothing heavier. An extractor whose preprocessing can't be described
this way simply returns None from ``farm_recipe()`` and the scheduler
falls back to in-process decode.
"""
from __future__ import annotations

from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

TransformSpec = Tuple  # ('edge_resize', size, interp) | ('edge_resize_crop', resize, crop, interp)


def resolve_transform(spec: Optional[TransformSpec]
                      ) -> Optional[Callable[[np.ndarray], np.ndarray]]:
    """Materialize a transform spec into a per-frame callable."""
    if spec is None:
        return None
    from video_features_tpu.ops.host_transforms import (
        center_crop_host, resize_pil,
    )
    kind = spec[0]
    if kind == 'edge_resize':
        _, size, interp = spec
        return lambda f: resize_pil(f, size, interpolation=interp)
    if kind == 'edge_resize_crop':
        _, resize, crop, interp = spec
        return lambda f: center_crop_host(
            resize_pil(f, resize, interpolation=interp), crop)
    raise ValueError(f'unknown transform spec {spec!r}')


class _LoaderRecipe:
    """Shared loader plumbing: builds the same ``io.video.VideoLoader``
    the in-process path builds (fps retiming backends, decode backend
    fallback, tmp-file lifecycle included) and guarantees ``close()``
    runs when iteration ends or is abandoned."""

    def __init__(self, batch_size: int, fps, total, tmp_path: str,
                 keep_tmp: bool, backend: str,
                 transform: Optional[TransformSpec]) -> None:
        self.batch_size = int(batch_size)
        self.fps = fps
        self.total = total
        self.tmp_path = str(tmp_path)
        self.keep_tmp = bool(keep_tmp)
        self.backend = backend
        self.transform = transform

    def _make_loader(self, path: str):
        from video_features_tpu.io.video import VideoLoader
        return VideoLoader(
            path, batch_size=self.batch_size, fps=self.fps,
            total=self.total, tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp,
            transform=resolve_transform(self.transform),
            backend=self.backend)


class FramewiseRecipe(_LoaderRecipe):
    """One window = one host-transformed frame; meta = its timestamp —
    mirrors ``BaseFrameWiseExtractor.packed_windows`` byte for byte
    (segment ranges included: same frame-index filter + early stop)."""

    def open(self, path: str, segment=None) -> Tuple[Dict, Iterator]:
        from video_features_tpu.extract.streaming import (
            framewise_segment_windows, segment_frame_range,
        )
        loader = self._make_loader(path)
        frame_range = segment_frame_range(segment, loader.fps)

        def windows():
            try:
                yield from framewise_segment_windows(loader, frame_range)
            finally:
                loader.close()

        return {'fps': loader.fps}, windows()


class FusedRecipe(_LoaderRecipe):
    """Multi-recipe mode: ONE raw decode pass per video, branched into
    every requested family's transform pipeline.

    The loader runs with ``transform=None`` (raw frames), and each
    decoded frame is pushed through every family's named-spec transform
    in declaration order — byte-identical to N per-family decodes
    because the in-process path applies its transform as a pure
    per-frame call over the very same decoded bytes
    (``io.video.VideoLoader``). Each yielded window is tagged with its
    family via ``meta = (family, t_ms)`` so the scheduler can route it
    to that family's pools/program; the farm transport ships meta
    opaquely, so no wire change is needed.

    ``select`` (an optional family subset, shipped as the task
    message's 5th element) lets the scheduler drop families that were
    answered from cache or already failed for this video — the shared
    decode still runs once for whoever remains.
    """

    def __init__(self, batch_size: int, fps, total, tmp_path: str,
                 keep_tmp: bool, backend: str,
                 transforms: 'Dict[str, Optional[TransformSpec]]') -> None:
        super().__init__(batch_size, fps, total, tmp_path, keep_tmp,
                         backend, transform=None)
        self.transforms = dict(transforms)     # family → spec, user order

    def family_of(self, meta) -> Optional[str]:
        """The family a ``(window, meta)`` pair belongs to — the farm
        consumer uses this to stamp per-family attrs on the shared
        decode spans."""
        if isinstance(meta, tuple) and len(meta) == 2:
            return meta[0]
        return None

    def open(self, path: str, segment=None,
             select=None) -> Tuple[Dict, Iterator]:
        from video_features_tpu.extract.streaming import (
            framewise_segment_windows, segment_frame_range,
        )
        loader = self._make_loader(path)
        frame_range = segment_frame_range(segment, loader.fps)
        fams = [f for f in self.transforms
                if select is None or f in select]
        branch = {f: resolve_transform(self.transforms[f]) for f in fams}

        def windows():
            try:
                for frame, t_ms in framewise_segment_windows(loader,
                                                             frame_range):
                    for fam in fams:
                        t = branch[fam]
                        yield ((t(frame) if t is not None else frame),
                               (fam, t_ms))
            finally:
                loader.close()

        return {'fps': loader.fps}, windows()


class StackRecipe(_LoaderRecipe):
    """One window = a ``(win, H, W, 3)`` frame stack stepped by ``step``
    — mirrors the stack families' ``packed_windows`` (r21d/s3d: raw
    frames, win = stack_size; i3d: win = stack_size + 1 and the host
    short-side resize unless ``device_resize`` lifted it in-graph)."""

    def __init__(self, win: int, step: int, batch_size: int, fps, total,
                 tmp_path: str, keep_tmp: bool, backend: str,
                 transform: Optional[TransformSpec]) -> None:
        super().__init__(batch_size, fps, total, tmp_path, keep_tmp,
                         backend, transform)
        self.win = int(win)
        self.step = int(step)

    def open(self, path: str, segment=None) -> Tuple[Dict, Iterator]:
        from video_features_tpu.extract.streaming import (
            segment_frame_range, stream_windows,
        )
        loader = self._make_loader(path)
        frame_range = segment_frame_range(segment, loader.fps)

        def windows():
            try:
                for window in stream_windows(loader, self.win, self.step,
                                             frame_range=frame_range):
                    yield window, None
            finally:
                loader.close()

        return {}, windows()
