"""Decode farm: multi-process decoder workers feeding the packer.

BENCH_r05 left the pipeline host-decode-bound (ingraph 9.69 clips/s vs
4.67 e2e): the in-process decoder is capped by the GIL and one process's
swscale. This subsystem runs N decoder worker PROCESSES — each driving
the exact decode + host-transform stack the in-process path runs
(``io/video.py`` + ``ops/host_transforms.py``) — and ships decoded
windows to the packed scheduler through bounded shared-memory byte
rings, so pixel data never takes the pickle hop.

Entry point: :class:`DecodeFarm` (``farm/farm.py``), consumed by
``parallel.packing.run_packed`` when ``decode_workers > 1`` and the
extractor publishes a picklable decode recipe (``farm/recipes.py``).
Contract: the farm's window stream is drop-in for
``extract.streaming.stream_windows_across_videos`` — same
``(task, window, meta)`` items, FLUSH/NUDGE sentinels, per-video fault
isolation, and ``task.emitted``/``exhausted`` accounting — so outputs
are byte-identical to ``decode_workers=1`` at any worker count.

See docs/decode_farm.md for architecture, SHM sizing, and knobs.
"""
from video_features_tpu.farm.farm import (  # noqa: F401
    DecodeFarm, FarmUnavailable, farm_available,
)
from video_features_tpu.farm.recipes import (  # noqa: F401
    FramewiseRecipe, StackRecipe,
)
