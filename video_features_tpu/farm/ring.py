"""Bounded shared-memory byte ring: one producer (a decode worker
process), one consumer (the farm's scheduler-side drain loop).

The ring is a plain byte arena over one ``multiprocessing.shared_memory``
segment. Positions are MONOTONIC byte counters (they never wrap); the
physical offset is ``pos % capacity``. The producer owns ``write_pos``;
the consumer reports consumed bytes back over a queue and the producer
folds them into ``read_pos`` — so neither side shares mutable state
beyond the segment bytes themselves, and a crashed producer can never
corrupt another worker's ring (each worker has its own segment and its
own queues).

Variable-size windows are handled with contiguous-region allocation: a
region never wraps mid-window; when the tail of the arena is too short,
the producer skips it and the skip rides along in the region's ``adv``
(total byte advance) so the consumer's in-order frees keep both sides'
arithmetic identical. Backpressure falls out of the arithmetic: when
``capacity - (write_pos - read_pos)`` can't fit the next window, the
producer blocks draining the free queue — a slow consumer stalls decode
instead of growing memory.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np


class RingFull(Exception):
    """Raised by :meth:`RingProducer.alloc` when ``wait_free`` gives up."""


class RingProducer:
    """Producer-side allocator over a SharedMemory segment's buffer."""

    def __init__(self, buf: memoryview, capacity: int) -> None:
        self.buf = buf
        self.capacity = int(capacity)
        self.write_pos = 0      # monotonic bytes allocated
        self.read_pos = 0       # monotonic bytes freed by the consumer

    def free_space(self) -> int:
        return self.capacity - (self.write_pos - self.read_pos)

    def freed(self, nbytes: int) -> None:
        """Fold a consumer free report (an ``adv`` value) into read_pos."""
        self.read_pos += int(nbytes)

    def alloc(self, nbytes: int,
              wait_free: Optional[Callable[[], None]] = None,
              ) -> Optional[Tuple[int, int]]:
        """Reserve a contiguous ``nbytes`` region → ``(offset, adv)``.

        ``adv`` is the total byte advance (region + any skipped arena
        tail) the consumer must report back verbatim. Returns None when
        the window can never fit (``nbytes > capacity``) — the caller
        falls back to shipping those bytes through the message queue.
        ``wait_free`` is called (blocking, typically draining the free
        queue) until space is available; it may raise to abort.
        """
        nbytes = int(nbytes)
        if nbytes * 2 > self.capacity:
            # a wrap's skipped tail can approach the window size, so a
            # window over half the arena could need adv > capacity —
            # unsatisfiable by any amount of freeing. Such windows take
            # the queue-transport fallback instead of deadlocking here.
            return None
        off = self.write_pos % self.capacity
        skip = self.capacity - off if off + nbytes > self.capacity else 0
        adv = skip + nbytes
        while self.free_space() < adv:
            if wait_free is None:
                raise RingFull(nbytes)
            wait_free()
        self.write_pos += adv
        return (self.write_pos - nbytes) % self.capacity, adv

    def write(self, offset: int, arr: np.ndarray) -> None:
        """Copy a C-contiguous array's bytes into the segment."""
        flat = arr.reshape(-1).view(np.uint8)
        dst = np.frombuffer(self.buf, dtype=np.uint8,
                            count=arr.nbytes, offset=offset)
        dst[:] = flat


def read_window(buf: memoryview, offset: int, shape: tuple,
                dtype: str) -> np.ndarray:
    """Consumer-side copy of one window out of the segment.

    The copy is deliberate: it frees the ring slot immediately (the
    producer can reuse it as soon as the ``adv`` free is reported), so
    ring capacity bounds only the *transport*, while the downstream
    prefetch/pool buffers keep their own existing bounds. The memcpy is
    ~three orders of magnitude cheaper than the decode it replaces and
    runs on the consumer's prefetch thread, overlapped with device
    compute.
    """
    n = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    src = np.frombuffer(buf, dtype=np.uint8, count=n, offset=offset)
    return src.copy().view(np.dtype(dtype)).reshape(shape)
