"""DecodeFarm: dispatcher + supervisor + the scheduler-facing stream.

The farm sits where ``extract.streaming.stream_windows_across_videos``
sits in the in-process pipeline: it consumes the scheduler's (possibly
blocking, FLUSH-punctuated) task stream and yields the same
``(task, window, meta)`` items — but decode runs in N worker PROCESSES
(``farm/worker.py``), each shipping windows through its own bounded
shared-memory ring (``farm/ring.py``).

Threading model (all in the parent):

  * the DISPATCHER thread consumes the task stream: runs the admission
    gate (resume skip / cache hit) per video, dedupes in-flight content
    (two tasks whose cache keys match decode ONCE — the second parks
    until the first finalizes and then re-runs the gate, which hits),
    and assigns videos to the least-loaded worker under a bounded
    runahead, preserving the lazy-resume-check property of the
    in-process path (never an up-front O(corpus) ``is_already_exist``
    scan);
  * the caller's thread (the packed scheduler's prefetch producer) runs
    :meth:`stream`'s drain loop: multiplexes every worker's message
    queue (``connection.wait`` over the queue pipes), copies windows out
    of SHM (freeing ring space immediately — the copy is ~1000× cheaper
    than the decode it replaces), maintains
    ``task.emitted/exhausted/failed`` and the FLUSH/NUDGE sentinel
    contract, and supervises workers: a dead process fails ONLY its
    in-flight video, its queued videos re-dispatch, and the worker
    respawns with a fresh ring epoch.

Fault model matches the per-video error contract everywhere: a decode
error or worker crash dooms exactly one video; the farm (and the
worklist) keep going. Only a systemic crash loop (``RESPAWN_LIMIT``
exceeded with no workers left) surfaces as a scheduler-level error,
which the serve layer already isolates per warm worker.
"""
from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from video_features_tpu.obs.context import trace_attrs
from video_features_tpu.utils.tracing import NULL_TRACER, Tracer

# total across the farm's lifetime; generous vs any real transient (a
# poison video costs at most 2: one mid-decode kill + one retry kill)
RESPAWN_LIMIT = 8

_MB = 1 << 20

# the vft_farm_* gauges are process-global while farms are per-run: a
# serve process can have several warm-pool entries each running a farm
# concurrently, so every gauge write must be an aggregate over the LIVE
# farms, not one instance's view (else last-writer-wins and a retiring
# entry zeroes a sibling's live workers out of the scrape)
_LIVE_FARMS: set = set()
_LIVE_LOCK = threading.Lock()
# thread-discipline declaration (vft-lint): module-level mutables and
# the lock that guards every access to them
_LOCKED_BY = {'_LIVE_FARMS': '_LIVE_LOCK'}


class FarmUnavailable(RuntimeError):
    """The host can't run the farm (no spawn context / SHM support)."""


def farm_available() -> bool:
    """Best-effort capability probe (import-level only — actual spawn
    failures still degrade gracefully at start())."""
    try:
        import multiprocessing.shared_memory  # noqa: F401
        import multiprocessing

        multiprocessing.get_context('spawn')
        return True
    except (ImportError, ValueError):
        return False


def _request_id(task) -> Optional[str]:
    req = getattr(task, 'request', None)
    return getattr(req, 'id', None)


class _Worker:
    __slots__ = ('idx', 'epoch', 'proc', 'shm', 'task_q', 'out_q',
                 'free_q', 'ctrl_q', 'pending', 'started', 'ring_used',
                 'aborted', 'clock_offset', 'clock_rtt', 'clock_asked')

    def __init__(self, idx: int, epoch: int) -> None:
        self.idx = idx
        self.epoch = epoch
        self.proc = None
        self.shm = None
        self.task_q = None
        self.out_q = None
        self.free_q = None
        self.ctrl_q = None
        self.pending: 'deque[int]' = deque()   # seqs assigned, FIFO
        self.started: set = set()              # seqs whose 'start' arrived
        self.aborted: set = set()              # seqs already sent an abort
        self.ring_used = 0                     # last-reported ring bytes
        # worker-clock → parent-clock offset ('clock' handshake; 0.0
        # until calibrated): added to every in-worker span timestamp so
        # the merged timeline shows true in-worker decode time. The
        # parent keeps the MINIMUM-round-trip measurement (NTP-style):
        # the startup exchange's round trip spans process spawn (its
        # midpoint would shift spans by ~spawn/2), so it only seeds the
        # offset until a tight in-decode refinement replaces it.
        self.clock_offset = 0.0
        self.clock_rtt = float('inf')          # best RTT seen (seconds)
        self.clock_asked = 0.0                 # last re-sync request t


class DecodeFarm:
    """N decode worker processes behind one cross-video window stream."""

    def __init__(self, recipe, workers: int = 2,
                 ring_bytes: int = 64 * _MB,
                 tracer: Tracer = NULL_TRACER,
                 cache_key_fn: Optional[Callable] = None,
                 respawn_limit: int = RESPAWN_LIMIT,
                 live_open: Optional[Callable] = None,
                 blackbox=None,
                 pending_cb: Optional[Callable] = None) -> None:
        import multiprocessing
        self.recipe = recipe
        # post-mortem dump target (obs/blackbox.BlackBox or None): a
        # dead worker process dumps a bundle alongside the respawn
        self._blackbox = blackbox
        # stall-watchdog feed (serve): ``pending_cb(worker_idx,
        # n_queued)`` mirrors each worker's assignment backlog so a
        # single wedged decode worker trips its own watchdog row even
        # while its siblings keep the serve-level row advancing
        self._pending_cb = pending_cb
        self.n_workers = max(int(workers), 1)
        self.ring_bytes = max(int(ring_bytes), _MB // 4)
        self.tracer = tracer
        self.cache_key_fn = cache_key_fn
        self.respawn_limit = int(respawn_limit)
        self._ctx = multiprocessing.get_context('spawn')
        self._lock = threading.Lock()
        self._ctrl: 'deque' = deque()          # FLUSH/NUDGE markers
        self._tasks: Dict[int, object] = {}    # seq → VideoTask
        self._next_seq = 0
        self._outstanding = 0                  # assigned, not yet ended
        self._unfinished: set = set()          # seqs assigned, not ended
        self._runahead = max(2 * self.n_workers, 4)
        self._inflight_keys: Dict[str, object] = {}
        self._parked: Dict[str, List] = {}
        self._retried: set = set()             # seqs given a post-crash retry
        self._respawns = 0
        self._stats = {'windows': 0, 'bytes': 0, 'queue_fallback': 0,
                       'videos_assigned': 0, 'videos_done': 0,
                       'videos_failed': 0, 'deduped': 0}
        self._workers: List[_Worker] = []
        # live tasks (ingress live sessions): windows arrive over the
        # network in the PARENT, so they never ship to a worker process.
        # ``live_open(task)`` returns the task's window iterator; each
        # live task gets a feeder thread appending to _live_out, which
        # the drain loop yields alongside worker windows.
        self._live_open = live_open
        self._live_out: 'deque' = deque()
        self._live_threads: List[threading.Thread] = []
        self._admit: Optional[Callable] = None
        self._dispatch_done = False
        self._dispatch_error: Optional[BaseException] = None
        self._stopping = False
        self._started = False
        from video_features_tpu.obs.metrics import REGISTRY
        self._g_workers = REGISTRY.gauge(
            'vft_farm_workers', 'decode farm worker processes alive')
        self._g_busy = REGISTRY.gauge(
            'vft_farm_busy_workers',
            'decode farm workers with videos assigned')
        self._g_ring = REGISTRY.gauge(
            'vft_farm_ring_bytes',
            'decoded bytes resident in the farm SHM rings')
        self._c_respawns = REGISTRY.counter(
            'vft_farm_respawns_total', 'decode farm worker respawns')

    # -- lifecycle -----------------------------------------------------------

    @staticmethod
    def _task_msg(seq: int, task) -> tuple:
        """The ONE builder of worker task messages — ('video', seq, path
        [, segment[, select]]). ``task.farm_select`` (fused worklists:
        the family subset still wanting this video's shared decode) is
        appended only when set, so plain recipes keep receiving the
        message shape they've always parsed."""
        select = getattr(task, 'farm_select', None)
        if select is not None:
            return ('video', seq, str(task.path),
                    getattr(task, 'segment', None), tuple(select))
        return ('video', seq, str(task.path),
                getattr(task, 'segment', None))

    def _spawn(self, idx: int, epoch: int,
               requeue: Iterable[int] = ()) -> _Worker:
        from multiprocessing import shared_memory

        from video_features_tpu.farm.worker import worker_main
        w = _Worker(idx, epoch)
        w.shm = shared_memory.SharedMemory(create=True,
                                           size=self.ring_bytes)
        w.task_q = self._ctx.Queue()
        w.out_q = self._ctx.Queue()
        w.free_q = self._ctx.Queue()
        w.ctrl_q = self._ctx.Queue()
        w.proc = self._ctx.Process(
            target=worker_main,
            args=(idx, epoch, self.recipe, w.shm.name, self.ring_bytes,
                  w.task_q, w.out_q, w.free_q, w.ctrl_q),
            daemon=True, name=f'vft-decode-{idx}')
        w.proc.start()
        # clock-calibration handshake: the worker reads this first (see
        # farm/worker.py) and answers with ('clock', ...) carrying its
        # own perf_counter reading — _handle computes the offset
        w.ctrl_q.put(('sync', time.perf_counter()))
        for seq in requeue:
            task = self._tasks[seq]
            w.pending.append(seq)
            w.task_q.put(self._task_msg(seq, task))
        return w

    def start(self) -> 'DecodeFarm':
        if self._started:
            return self
        try:
            self._workers = [self._spawn(i, 0)
                             for i in range(self.n_workers)]
        except Exception as e:
            self.shutdown()
            raise FarmUnavailable(f'decode farm failed to start: {e}')
        self._started = True
        with _LIVE_LOCK:
            _LIVE_FARMS.add(self)
        self._update_gauges()
        return self

    def shutdown(self) -> None:
        """Idempotent teardown: stop workers, reap processes, unlink SHM."""
        self._stopping = True
        for w in self._workers:
            if w.proc is not None and w.proc.is_alive():
                try:
                    w.task_q.put(('stop',))
                except Exception:
                    # vft-lint: ok=swallowed-exception — best-effort stop
                    # to a possibly-dead child; join below bounds teardown
                    pass
        deadline = time.monotonic() + 5.0
        for w in self._workers:
            if w.proc is not None:
                w.proc.join(max(0.0, deadline - time.monotonic()))
                if w.proc.is_alive():
                    w.proc.terminate()
                    w.proc.join(1.0)
        for w in self._workers:
            self._close_ring(w)
        if self._pending_cb is not None:
            # zero the watchdog rows: a retired farm's stale backlog
            # must not read as a stall after the run ends. The pending
            # deques are CLEARED first — _update_gauges below mirrors
            # len(w.pending) through the same callback, and republishing
            # a dead worker's backlog would undo this zeroing
            for w in self._workers:
                with self._lock:
                    w.pending.clear()
                try:
                    self._pending_cb(w.idx, 0)
                except Exception:
                    # vft-lint: ok=swallowed-exception — teardown-path
                    # liveness hook; the forget on worker retirement
                    # clears the rows regardless
                    pass
        with _LIVE_LOCK:
            _LIVE_FARMS.discard(self)
        self._update_gauges()

    @staticmethod
    def _close_ring(w: _Worker) -> None:
        w.ring_used = 0
        if w.shm is not None:
            try:
                w.shm.close()
                w.shm.unlink()
            except Exception:
                # vft-lint: ok=swallowed-exception — idempotent teardown:
                # a ring already unlinked by a respawn raises harmlessly
                pass
            w.shm = None

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._stats)
            out['decode_workers'] = self.n_workers
            out['alive_workers'] = sum(
                1 for w in self._workers
                if w.proc is not None and w.proc.is_alive())
            out['busy_workers'] = sum(1 for w in self._workers if w.pending)
            out['ring_bytes_in_use'] = sum(w.ring_used
                                           for w in self._workers)
            out['respawns'] = self._respawns
            out['ring_bytes_capacity'] = self.ring_bytes * self.n_workers
        return out

    def _update_gauges(self) -> None:
        with _LIVE_LOCK:
            farms = list(_LIVE_FARMS)
        self._g_workers.set(sum(
            1 for f in farms for w in f._workers
            if w.proc is not None and w.proc.is_alive()))
        self._g_busy.set(sum(
            1 for f in farms for w in f._workers if w.pending))
        self._g_ring.set(sum(
            w.ring_used for f in farms for w in f._workers))
        if self._pending_cb is not None:
            with self._lock:
                backlog = [(w.idx, len(w.pending)) for w in self._workers]
            for idx, n in backlog:
                try:
                    self._pending_cb(idx, n)
                except Exception:
                    # vft-lint: ok=swallowed-exception — a broken
                    # liveness hook must not take down the drain loop
                    pass

    # -- dispatcher ----------------------------------------------------------

    def _dispatch(self, tasks: Iterable, admit: Callable) -> None:
        from video_features_tpu.parallel.packing import FLUSH
        try:
            for item in tasks:
                if item is FLUSH:
                    self._append_flush()
                    continue
                task = item
                if getattr(task, 'windows_override', None) is not None \
                        and self._live_open is not None:
                    # live session: no file to decode — run its window
                    # source on a parent-side feeder thread
                    self._start_live(task)
                    continue
                if not self._gate(task, admit):
                    continue
                key = None
                if self.cache_key_fn is not None:
                    seg = getattr(task, 'segment', None)
                    try:
                        # segment passed only when set: a range task must
                        # never dedupe against its full-video twin, and
                        # pre-segment key fns keep working for whole
                        # videos
                        key = (self.cache_key_fn(str(task.path), seg)
                               if seg is not None
                               else self.cache_key_fn(str(task.path)))
                    except Exception:
                        # vft-lint: ok=swallowed-exception — fallback by
                        # design: an unhashable video skips dedupe and
                        # decodes normally (its own failure reports there)
                        key = None             # unhashable → no dedupe
                with self._lock:
                    twin = (self._inflight_keys.get(key)
                            if key is not None else None)
                    if twin is not None and not getattr(twin, 'finalized',
                                                        False):
                        # same content is decoding right now (another
                        # request, a duplicate worklist entry): park
                        # until the twin publishes, then the gate's
                        # cache consult answers this one for free
                        self._parked.setdefault(key, []).append(task)
                        self._stats['deduped'] += 1
                        continue
                    if key is not None:
                        self._inflight_keys[key] = task
                self._assign(task)
            # resolve parked duplicates + wait for the field to clear
            last_flush = 0.0
            while not self._stopping:
                self._resolve_parked(admit)
                with self._lock:
                    busy = (self._outstanding > 0
                            or any(self._parked.values())
                            or any(t.is_alive()
                                   for t in self._live_threads))
                if not busy:
                    break
                if any(self._parked.values()) \
                        and time.monotonic() - last_flush > 0.05:
                    # a parked twin may be waiting on a tail pool: force
                    # the packer to flush so the twin can finalize
                    self._append_flush()
                    last_flush = time.monotonic()
                time.sleep(0.02)
        # vft-lint: ok=swallowed-exception — stored, not swallowed: the
        # drain loop re-raises _dispatch_error to the caller
        except BaseException as e:            # surfaced by the drain loop
            self._dispatch_error = e
        finally:
            self._dispatch_done = True

    def _prune_live(self) -> None:
        """Drop finished feeder threads — a serve farm lives for the
        server's lifetime, so an append-only list would retain a dead
        Thread (and an is_alive scan) per live session forever."""
        with self._lock:
            self._live_threads = [t for t in self._live_threads
                                  if t.is_alive()]

    def _start_live(self, task) -> None:
        t = threading.Thread(target=self._feed_live, args=(task,),
                             daemon=True, name='vft-farm-live')
        self._prune_live()
        with self._lock:
            self._live_threads.append(t)
        t.start()

    def _feed_live(self, task) -> None:
        """Feeder thread for one live task: runs its window source (the
        session's network-fed windower) and hands windows to the drain
        loop via ``_live_out``, bounded so a stalled consumer
        backpressures the session instead of growing parent memory. The
        per-video error contract holds: a feeder failure dooms exactly
        this task."""
        from video_features_tpu.extract.base import log_extraction_error
        from video_features_tpu.parallel.packing import FLUSH
        try:
            for item in self._live_open(task):
                if self._stopping or task.failed:
                    break
                if item is FLUSH:
                    # arrival lull: flush partial pools so computed
                    # windows stream back (watermarked like any FLUSH,
                    # so it never overtakes windows still decoding)
                    self._append_flush()
                    continue
                window, meta = item
                task.emitted += 1
                while len(self._live_out) >= 64 and not self._stopping:
                    time.sleep(0.005)
                self._live_out.append((task, window, meta))
        except Exception:
            task.failed = True
            log_extraction_error(task.path, stage='decode',
                                 request_id=_request_id(task))
        finally:
            task.exhausted = True
            if task.emitted == 0:
                self._ctrl.append(('nudge', task))
            else:
                # flush the session's tail windows out of the pools now —
                # the stream may not see another FLUSH for a long time
                self._append_flush()

    def _append_flush(self) -> None:
        """Queue a FLUSH marker with a watermark: the in-process windower
        yields FLUSH only AFTER the windows of every task that preceded
        it in the stream, so the farm must not let a FLUSH overtake
        windows still decoding in the workers — a serve feed that goes
        idle right after its last FLUSH would otherwise leave the late
        windows pooled in the packer forever. The drain loop holds the
        marker until every seq assigned before it has ended."""
        with self._lock:
            self._ctrl.append(('flush', self._next_seq))

    def _gate(self, task, admit: Callable) -> bool:
        """Admission gate (resume skip / cache hit / gate failure) —
        False means the video is terminal without decoding (NUDGE)."""
        from video_features_tpu.extract.base import log_extraction_error
        try:
            go = admit(task)
        except KeyboardInterrupt:
            raise
        except Exception:
            task.failed = True
            log_extraction_error(task.path, stage='decode',
                                 request_id=_request_id(task))
            go = False
        if not go:
            task.exhausted = True
            self._ctrl.append(('nudge', task))
            return False
        return True

    def _pick_worker(self) -> Optional[_Worker]:
        """Least-loaded alive worker, or None. Caller holds the lock."""
        alive = [w for w in self._workers
                 if w.proc is not None and w.proc.is_alive()]
        return min(alive, key=lambda w: len(w.pending)) if alive else None

    def _assign(self, task, block: bool = True) -> bool:
        """Hand the video to a worker. ``block=False`` (drain-thread
        callers only) returns False instead of waiting when the runahead
        window is full — the drain thread is the one that shrinks
        ``_outstanding``, so blocking there would deadlock the farm."""
        while not self._stopping:
            with self._lock:
                if self._outstanding < self._runahead:
                    self._outstanding += 1
                    break
            if not block:
                return False
            time.sleep(0.01)
        if self._stopping:
            return True
        with self._lock:
            target = self._pick_worker()
            if target is None:
                # systemic: no workers left (respawn budget burned) —
                # fail the video through the normal per-video contract
                task.failed = True
                task.exhausted = True
                self._outstanding -= 1
                # videos_done counts every ENDED video, failures
                # included (videos_failed ⊆ videos_done — serving.md
                # documents backlog math on that invariant)
                self._stats['videos_done'] += 1
                self._stats['videos_failed'] += 1
                self._ctrl.append(('nudge', task))
                return True
            seq = self._next_seq
            self._next_seq += 1
            self._tasks[seq] = task
            self._unfinished.add(seq)
            target.pending.append(seq)
            self._stats['videos_assigned'] += 1
        target.task_q.put(self._task_msg(seq, task))
        return True

    def _resolve_parked(self, admit: Callable,
                        block: bool = True) -> None:
        """Unpark duplicates whose twin has finalized. Runs on BOTH
        threads: the dispatcher's post-source loop (``block=True``), and
        the drain loop's supervise tick (``block=False``) — the latter
        is what keeps a serve feed honest, where the task stream never
        ends and a concurrent-duplicate request would otherwise stay
        parked until server drain."""
        with self._lock:
            ready = [key for key, twin in self._inflight_keys.items()
                     if getattr(twin, 'finalized', False)]
            # keys parked with NO inflight twin (a failed non-blocking
            # assign below re-parks this way) are ready by definition
            ready += [key for key in self._parked
                      if key not in self._inflight_keys]
        for key in ready:
            with self._lock:
                waiters = self._parked.pop(key, [])
                self._inflight_keys.pop(key, None)
            for task in waiters:
                # the gate re-runs: if the twin published, the cache
                # consult materializes this video without a decode
                if not self._gate(task, admit):
                    continue
                with self._lock:
                    twin = self._inflight_keys.get(key)
                    if twin is not None and not getattr(
                            twin, 'finalized', False):
                        self._parked.setdefault(key, []).append(task)
                        continue
                    self._inflight_keys[key] = task
                if not self._assign(task, block=block):
                    # runahead full (non-blocking caller): put it back
                    # exactly as it was and retry on a later tick
                    with self._lock:
                        if self._inflight_keys.get(key) is task:
                            del self._inflight_keys[key]
                        self._parked.setdefault(key, []).append(task)

    # -- the scheduler-facing stream -----------------------------------------

    def stream(self, tasks: Iterable, admit: Callable) -> Iterator:
        """Yield ``(task, window, meta)`` / FLUSH / NUDGE across the
        whole task stream — the drop-in replacement for
        ``stream_windows_across_videos`` + ``prefetch_across_videos``'s
        producer side (windows still flow through the scheduler's
        prefetch buffer downstream)."""
        self.start()
        self._admit = admit
        dispatcher = threading.Thread(
            target=self._dispatch, args=(tasks, admit),
            daemon=True, name='vft-farm-dispatch')
        dispatcher.start()
        try:
            yield from self._drain()
            if self._dispatch_error is not None:
                raise self._dispatch_error
        finally:
            self.shutdown()

    def _drain(self) -> Iterator:
        from multiprocessing.connection import wait as conn_wait

        from video_features_tpu.parallel.packing import FLUSH, NUDGE
        last_supervise = 0.0
        while True:
            # live-session windows first: produced parent-side, they
            # should reach the packer before any lull FLUSH queued after
            # them flushes the pools
            while self._live_out:
                yield self._live_out.popleft()
            while self._ctrl:
                marker = self._ctrl[0]
                if marker[0] == 'flush':
                    # ordering barrier (see _append_flush): hold the
                    # FLUSH — and, to keep marker FIFO, everything
                    # behind it — until every seq assigned before the
                    # marker has ended
                    watermark = marker[1]
                    with self._lock:
                        blocked = any(s < watermark
                                      for s in self._unfinished)
                    if blocked:
                        break
                    self._ctrl.popleft()
                    yield FLUSH
                else:
                    self._ctrl.popleft()
                    yield NUDGE
            with self._lock:
                drained = (self._dispatch_done and self._outstanding == 0
                           and not self._ctrl and not self._live_out
                           and not any(t.is_alive()
                                       for t in self._live_threads))
            if drained and not self._ctrl:
                if self._dispatch_error is None:
                    # surface any last accounting before ending
                    pass
                return
            # Queue._reader is CPython-private (the queue's underlying
            # read Connection) — the only handle connection.wait can
            # multiplex on. Guarded: a runtime without it just degrades
            # to the 20ms poll below, never an AttributeError.
            readers = [r for w in self._workers if w.proc is not None
                       for r in (getattr(w.out_q, '_reader', None),)
                       if r is not None]
            if readers:
                try:
                    conn_wait(readers, timeout=0.05)
                except OSError:
                    time.sleep(0.02)
            else:
                time.sleep(0.02)
            for w in list(self._workers):
                yield from self._drain_worker(w)
            now = time.monotonic()
            if now - last_supervise >= 0.2:
                last_supervise = now
                yield from self._supervise()
                # unpark duplicates whose twin finalized — on the DRAIN
                # thread because a serve feed never ends, so the
                # dispatcher's post-source resolve loop never runs there
                # (non-blocking: this thread must never wait on the
                # runahead window it is responsible for shrinking)
                self._resolve_parked(self._admit, block=False)
                self._prune_live()
                self._update_gauges()

    def _drain_worker(self, w: _Worker) -> Iterator:
        while True:
            try:
                msg = w.out_q.get_nowait()
            except queue_mod.Empty:
                return
            except (OSError, EOFError):
                return                        # feeder died mid-message
            item = self._handle(w, msg)
            if item is not None:
                yield item

    def _handle(self, w: _Worker, msg: tuple):
        """Process one worker message; returns a stream item or None."""
        from video_features_tpu.farm.ring import read_window
        from video_features_tpu.parallel.packing import NUDGE
        kind, widx, epoch = msg[0], msg[1], msg[2]
        if epoch != w.epoch:
            return None                       # stale pre-respawn message
        if kind == 'clock':
            # calibration reply (midpoint method, minimum-RTT filtered):
            # the worker echoed our t_parent0 with its own clock; the
            # midpoint's error is bounded by HALF THE ROUND TRIP, so
            # only the tightest exchange ever seen updates the offset —
            # the startup exchange (whose round trip spans process
            # spawn) seeds it, and the first in-decode re-sync (the
            # worker polls ctrl every window) replaces it with a
            # millisecond-grade measurement. Spans recorded before any
            # reply stay at offset 0 — perf_counter is process-shared
            # on Linux, so that degradation is benign.
            t_parent0, t_worker = msg[3], msg[4]
            rtt = time.perf_counter() - t_parent0
            if rtt < w.clock_rtt:
                w.clock_rtt = rtt
                w.clock_offset = ((t_parent0 + time.perf_counter()) / 2.0
                                  - t_worker)
            return None
        if kind == 'start':
            seq, info = msg[3], msg[4]
            task = self._tasks.get(seq)
            w.started.add(seq)
            if task is not None and info:
                task.info.update(info)
            return None
        if kind in ('win', 'winq'):
            if kind == 'win':
                seq, off, adv, shape, dtype, meta, t0, dt, used = msg[3:]
                window = read_window(w.shm.buf, off, shape, dtype)
                w.free_q.put(adv)
                w.ring_used = used            # producer-reported occupancy
                with self._lock:
                    self._stats['bytes'] += window.nbytes
            else:
                seq, payload, shape, dtype, meta, t0, dt = msg[3:]
                window = np.frombuffer(
                    payload, dtype=np.dtype(dtype)).reshape(shape)
                try:
                    # credit the queue-transport slot back (see
                    # MAX_UNACKED_WINQ in farm/worker.py) — sent for
                    # every consumed 'winq' regardless of task state,
                    # it is transport accounting, not video accounting
                    w.ctrl_q.put(('winq_ack',))
                except Exception:
                    # vft-lint: ok=swallowed-exception — ack to a dead
                    # worker; the supervisor reaps it on the next tick
                    pass
                with self._lock:
                    self._stats['queue_fallback'] += 1
                    self._stats['bytes'] += window.nbytes
            if w.clock_rtt > 0.05 \
                    and time.monotonic() - w.clock_asked > 0.5:
                # calibration still coarse (the startup exchange spans
                # spawn): re-sync NOW, while the worker is provably in
                # its decode loop polling ctrl every window — this
                # round trip is tight, and min-RTT filtering keeps it
                w.clock_asked = time.monotonic()
                try:
                    w.ctrl_q.put(('sync', time.perf_counter()))
                except Exception:
                    # vft-lint: ok=swallowed-exception — re-sync to a
                    # dying worker; supervision reaps it, spans keep
                    # the seed offset
                    pass
            task = self._tasks.get(seq)
            if task is None:
                return None
            if task.failed:
                # device-side fault mid-video: stop paying decode for
                # the rest of it (same early-stop the in-process
                # windower applies), drop the window
                if seq not in w.aborted:
                    w.aborted.add(seq)
                    try:
                        w.ctrl_q.put(('abort', seq))
                    except Exception:
                        # vft-lint: ok=swallowed-exception — abort to a
                        # dead worker; supervision handles the corpse
                        pass
                return None
            task.emitted += 1
            with self._lock:
                self._stats['windows'] += 1
            if self.tracer.enabled:
                # per-worker provenance + transport occupancy: which
                # process decoded this window and how full its SHM ring
                # ran (ring_used ≈ capacity ⇒ the consumer is the wall,
                # not decode). The span is placed at the WORKER's
                # clock-calibrated start and attributed to the worker's
                # own pid/lane — the merged timeline shows true
                # in-worker decode time, not parent-side drain time.
                # Fused recipes tag each window with its family
                # (recipe.family_of) so the SHARED decode span set still
                # answers "which family was this window for".
                fam_attr = {}
                fam_of = getattr(self.recipe, 'family_of', None)
                if fam_of is not None:
                    fam = fam_of(meta)
                    if fam is not None:
                        fam_attr['family'] = fam
                self.tracer.add('decode', dt,
                                **fam_attr,
                                t0=t0 + w.clock_offset,
                                span_pid=(w.proc.pid
                                          if w.proc is not None else None),
                                span_tid=widx,
                                video=str(task.path), worker=widx,
                                ring_used=w.ring_used,
                                ring_capacity=self.ring_bytes,
                                request_id=_request_id(task),
                                **trace_attrs(task))
            return task, window, meta
        if kind in ('end', 'err'):
            seq = msg[3]
            task = self._tasks.get(seq)
            self._finish_seq(w, seq)
            if task is None:
                return None
            if kind == 'err':
                task.failed = True
                self._report_decode_error(task, msg[4])
            task.exhausted = True
            with self._lock:
                self._stats['videos_done'] += 1
                if task.failed:
                    self._stats['videos_failed'] += 1
            if task.emitted == 0:
                return NUDGE
            return None
        return None

    def _finish_seq(self, w: _Worker, seq: int) -> None:
        with self._lock:
            try:
                w.pending.remove(seq)
            except ValueError:
                pass
            w.started.discard(seq)
            w.aborted.discard(seq)
            self._unfinished.discard(seq)
            self._retried.discard(seq)
            # drop the task ref: on a serve farm (one run for the
            # server's lifetime) seq→task entries would otherwise
            # accumulate per request forever. Callers that need the task
            # fetch it BEFORE finishing the seq; late messages from the
            # same epoch can't reference an ended seq (per-video 'end'
            # is the worker's last message for it), and stale-epoch
            # messages are dropped before task lookup.
            self._tasks.pop(seq, None)
            self._outstanding -= 1

    def _report_decode_error(self, task, tb_text: str) -> None:
        from video_features_tpu.obs.events import event
        event(logging.WARNING,
              f'decode farm worker failed {task.path}:\n{tb_text}',
              video=str(task.path), stage='decode',
              request_id=_request_id(task))

    # -- supervision ---------------------------------------------------------

    def _supervise(self) -> Iterator:
        """Detect dead workers; fail their in-flight video, re-dispatch
        their queue, respawn under the budget."""
        from video_features_tpu.parallel.packing import NUDGE
        for i, w in enumerate(list(self._workers)):
            if w.proc is None or w.proc.is_alive() or self._stopping:
                continue
            # drain every message it managed to send before dying
            yield from self._drain_worker(w)
            with self._lock:
                pending = list(w.pending)
            victim_seq = None
            requeue: List[int] = []
            if pending:
                oldest = pending[0]
                if oldest in w.started or oldest in self._retried:
                    # mid-decode (or burned its one retry): this video
                    # dies, the per-video contract's single casualty
                    victim_seq = oldest
                    requeue = pending[1:]
                else:
                    # can't prove it ever started — give it ONE retry so
                    # a queued-but-untouched video isn't lost, while a
                    # poison video still fails on its second crash
                    self._retried.add(oldest)
                    requeue = pending
            from video_features_tpu.obs.events import event
            event(logging.WARNING,
                  f'decode farm worker {w.idx} died '
                  f'(exitcode {w.proc.exitcode}); '
                  f'{"failing " + str(self._tasks[victim_seq].path) if victim_seq is not None else "no video in flight"}'
                  f'; respawning with {len(requeue)} queued video(s)',
                  subsystem='farm')
            if self._blackbox is not None:
                # post-mortem bundle for the dead worker: the spans it
                # shipped before dying are already in the ring (at most
                # its in-flight video's tail is lost), the event above
                # is in the tail — dump both. Never raises, never on
                # the request hot path (supervise tick only).
                self._blackbox.dump(
                    'farm_worker_death', worker=w.idx,
                    exitcode=w.proc.exitcode,
                    victim=(str(self._tasks[victim_seq].path)
                            if victim_seq is not None else None),
                    requeued=len(requeue))
            if victim_seq is not None:
                task = self._tasks[victim_seq]
                task.failed = True
                task.exhausted = True
                self._finish_seq(w, victim_seq)
                with self._lock:
                    self._stats['videos_done'] += 1
                    self._stats['videos_failed'] += 1
                if task.emitted == 0:
                    yield NUDGE
            self._close_ring(w)
            with self._lock:
                over_budget = self._respawns >= self.respawn_limit
                if not over_budget:
                    # counted only when a respawn actually happens —
                    # retired-past-budget workers must not inflate
                    # vft_farm_respawns_total during the very crash
                    # loop it exists to diagnose
                    self._respawns += 1
                w.pending.clear()
                w.started.clear()
            # requeued videos STAY outstanding throughout — they were
            # assigned, they remain assigned, only the queue they sit in
            # changes; accounting moves only for the failed victim(s)
            if not over_budget:
                self._c_respawns.inc()
                self._workers[i] = self._spawn(w.idx, w.epoch + 1,
                                               requeue=requeue)
            else:
                event(logging.WARNING,
                      f'decode farm respawn budget exhausted '
                      f'({self.respawn_limit}); worker {w.idx} stays down',
                      subsystem='farm')
                # reap the corpse and retire the slot — proc=None takes
                # this worker out of every alive/reader scan, so the
                # next supervise tick doesn't re-enter the dead-worker
                # path (and re-count a respawn) every 0.2s forever
                try:
                    w.proc.join(0.1)
                except Exception:
                    # vft-lint: ok=swallowed-exception — reaping a corpse;
                    # the retirement below is what matters
                    pass
                w.proc = None
                # re-dispatch its queue to surviving workers (or fail)
                for seq in requeue:
                    task = self._tasks[seq]
                    with self._lock:
                        target = self._pick_worker()
                        if target is not None:
                            target.pending.append(seq)
                    if target is not None:
                        target.task_q.put(self._task_msg(seq, task))
                    else:
                        task.failed = True
                        task.exhausted = True
                        with self._lock:
                            self._outstanding -= 1
                            self._unfinished.discard(seq)
                            self._retried.discard(seq)
                            self._tasks.pop(seq, None)
                            self._stats['videos_done'] += 1
                            self._stats['videos_failed'] += 1
                        if task.emitted == 0:
                            yield NUDGE
            self._update_gauges()


def merge_farm_stats(stats: Iterable[Dict[str, float]]) -> Dict[str, float]:
    """Sum farm stats dicts across serve workers (the serve metrics
    document's ``farm`` section); always returns the full key set so
    scrapers see zeros before the first farm-enabled request."""
    out: Dict[str, float] = {
        'decode_workers': 0, 'alive_workers': 0, 'busy_workers': 0,
        'ring_bytes_in_use': 0, 'ring_bytes_capacity': 0, 'respawns': 0,
        'windows': 0, 'bytes': 0, 'queue_fallback': 0,
        'videos_assigned': 0, 'videos_done': 0, 'videos_failed': 0,
        'deduped': 0}
    for s in stats:
        if not s:
            continue
        for k in out:
            out[k] += int(s.get(k, 0))
    return out
