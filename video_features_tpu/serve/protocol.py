"""Wire protocol for the warm-pool extraction service: JSON lines over a
local TCP socket.

One request per line, one response per line, UTF-8, newline-delimited —
the simplest framing that composes with ``socket.makefile`` buffering,
survives partial reads, and stays debuggable with ``nc``/``telnet``. The
endpoint binds loopback only; this is a LOCAL control surface (same
trust domain as the process), not an internet-facing API.

Versioning: every message MAY carry a ``v`` field (``'<major>.<minor>'``;
:data:`VERSION` is what this build speaks, :data:`MAJOR` the compatible
major). A missing ``v`` is treated as v1 (pre-versioning clients keep
working); an unknown MAJOR is rejected with a structured error that
echoes the message's ``request_id`` (when present) instead of a silent
parse failure — see :func:`check_version`. Minor-version skew is always
accepted (additive fields only).

Commands (the ``cmd`` field):

  * ``submit``  — ``{cmd, feature_type, video_paths: [..],
    overrides: {..}, timeout_s, range: [start_s, end_s], priority}`` →
    ``{ok, request_id}`` or ``{ok: false, error}``. ``overrides`` merge
    over the server's base overrides and the feature YAML exactly like
    CLI dotlist keys. ``range`` (optional) makes this a SEGMENT query:
    only the windows overlapping the time range are decoded/extracted,
    and outputs are named ``<stem>_seg<start>-<end>ms``. ``priority``
    (``interactive``, the default, or ``batch``) feeds admission
    control: a saturated queue sheds ``batch`` before ``interactive``.
    ``traceparent`` (optional, W3C ``00-<trace>-<span>-<flags>``) joins
    the request to a caller-owned distributed trace; absent or
    malformed, the server mints one. The submit response echoes the
    ``trace_id`` either way. ``features`` (optional, v1.2) submits a
    FUSED multi-family request: one umbrella request id plus a
    ``requests`` map of per-family child ids in the response
    (``feature_type`` is ignored when present); family-scoped override
    keys spell ``<family>.<knob>``.
  * ``status``  — ``{cmd, request_id}`` → per-request state + per-video
    states (see ``serve.server.Request.snapshot``).
  * ``trace``   — ``{cmd, request_id}`` → ``{ok, request_id, trace_id,
    events}``: the request's assembled span timeline, filtered from the
    live recorders (``serve.server.ExtractionServer.request_trace``).
    Against the FLEET ROUTER (v1.5) the assembly is scatter-gather —
    router spans plus every attempted backend's spans merged ts-sorted
    under one trace_id, with per-event ``host`` attrs and an additive
    ``hosts`` response field listing the contributors.
  * ``metrics`` — ``{cmd}`` → the live metrics document
    (``docs/serving.md`` schema; v1.5 adds the ``slo`` section).
  * ``metrics_prom`` — ``{cmd}`` → ``{ok, text}``: the same state as
    Prometheus text exposition format 0.0.4 (``docs/observability.md``).
    Against the FLEET ROUTER (v1.5): the fleet-aggregated exposition —
    every backend's families relabeled ``host=`` and merged with the
    router's ``vft_fleet_*`` / ``vft_slo_*`` families.
  * ``search`` — (v1.3) query the feature index. By vector:
    ``{cmd, family, vector: [..], k}``; by video: ``{cmd, video_path,
    features: [..], k, timeout_s}`` (extracts through the fused submit
    path, waits for ingest, queries with the video's own windows) →
    ``{ok, hits | results}`` with per-hit ``{score, video,
    video_sha256, t_ms, key, family}``. Requires ``index_enabled``.
  * ``index_status`` — (v1.3) ``{cmd}`` → the index section of the
    metrics document (rows, shards, ingest lag, program residency).
  * ``drain``   — stop admitting, finish everything queued, shut down.
  * ``ping``    — liveness probe.
"""
from __future__ import annotations

import json
from typing import Any, Dict

# command-name constants: the ONE spelling of each command. The server
# dispatch and ServeClient build their messages from these (vft-lint's
# wire-literal rule rejects inline command strings in serve/), and the
# vft-wire extractor (analysis/wire.py) anchors its static command
# enumeration here — an inline 'submit' string would be invisible to it.
CMD_SUBMIT = 'submit'
CMD_STATUS = 'status'
CMD_TRACE = 'trace'
CMD_METRICS = 'metrics'
CMD_METRICS_PROM = 'metrics_prom'
CMD_SEARCH = 'search'
CMD_INDEX_STATUS = 'index_status'
CMD_DRAIN = 'drain'
CMD_PING = 'ping'

COMMANDS = (CMD_SUBMIT, CMD_STATUS, CMD_TRACE, CMD_METRICS,
            CMD_METRICS_PROM, CMD_SEARCH, CMD_INDEX_STATUS, CMD_DRAIN,
            CMD_PING)

# wire protocol version this build speaks; MAJOR is the compatibility
# gate (minor bumps are additive-fields-only and never rejected).
# History: 1.0 introduced versioning itself (check_version + client `v`
# stamping); 1.1 is the first real MINOR bump, retroactively covering
# the additive `trace` command / `/v1/requests/<id>/trace` route that
# landed without a bump — exactly the drift WIRE.lock.json now catches;
# 1.2 adds the optional `features` submit field (fused multi-family
# requests: one request id, per-family children, `requests`/`errors`
# in the response and nested per-family `videos` in status);
# 1.3 adds the feature-index surface: the `search` / `index_status`
# commands and the ingress `POST /v1/search` route (query-by-vector
# and query-by-video over the sharded embedding index);
# 1.4 adds the additive `code` field on error responses (the ERR_*
# constants below): the fleet router's failover decision — retry the
# hash ring's next host vs propagate to the caller — keys on the code,
# never on the human-readable message text;
# 1.5 (vft-scope) adds the fleet observability plane, all additive:
# the router answers `metrics_prom` with the fleet-aggregated
# exposition (host-relabeled backend families + vft_fleet_*/vft_slo_*),
# its `trace` response gains `hosts` and per-event `host` attrs
# (cross-host scatter-gather assembly), and the metrics document gains
# the `slo` section (burn-rate objectives, obs/slo.py).
VERSION = '1.5'
MAJOR = 1

# submit() fields copied verbatim into the request (everything else in the
# message is rejected — catches client/server schema drift loudly)
SUBMIT_FIELDS = ('cmd', 'v', 'feature_type', 'video_paths', 'overrides',
                 'timeout_s', 'range', 'priority', 'traceparent',
                 'features')

PRIORITIES = ('interactive', 'batch')

# structured error codes (wire 1.4, the additive `code` response field).
# Server-side rejections carry one of the first group; the CLIENT mints
# the second group for failures that never reached a server response, so
# one switch in the router covers both. Failover semantics
# (fleet/router.py): `shed`, `connect_refused`, and `deadline` are
# retry-next-host; everything else propagates to the caller — a request
# the whole fleet would reject identically must not be retried N times.
ERR_SHED = 'shed'                      # queue_full / draining admission
ERR_INVALID = 'invalid'                # malformed or unknown-field request
ERR_UNSUPPORTED = 'unsupported'        # version skew / disabled subsystem
ERR_NOT_FOUND = 'not_found'            # unknown request_id
ERR_INTERNAL = 'internal'              # handler raised
ERR_CONNECT_REFUSED = 'connect_refused'  # client-minted: no listener
ERR_DEADLINE = 'deadline'              # client-minted: timed out waiting


def encode(msg: Dict[str, Any]) -> bytes:
    """One wire frame. Rejects objects whose JSON would embed a newline
    (impossible for json.dumps output, but the assert documents the
    framing invariant the reader relies on)."""
    line = json.dumps(msg, separators=(',', ':'))
    assert '\n' not in line
    return line.encode('utf-8') + b'\n'


def decode(line: bytes) -> Dict[str, Any]:
    msg = json.loads(line.decode('utf-8'))
    if not isinstance(msg, dict):
        raise ValueError('protocol messages must be JSON objects')
    return msg


def check_version(msg: Dict[str, Any]) -> 'Dict[str, Any] | None':
    """None when the message's protocol version is compatible, else the
    structured rejection to send back: names the offered and supported
    versions and echoes the message's ``request_id`` (when it carries
    one) so a multiplexing client can correlate the failure. A missing
    ``v`` is v1 (pre-versioning clients); a malformed one is rejected
    like an unknown major — both fail LOUDLY, never as a parse error."""
    v = msg.get('v')
    if v is None:
        return None
    try:
        major = int(str(v).split('.', 1)[0])
    except (TypeError, ValueError):
        return error(f'malformed protocol version {v!r} '
                     f'(server speaks {VERSION})',
                     code=ERR_UNSUPPORTED, v=VERSION,
                     request_id=msg.get('request_id'))
    if major != MAJOR:
        return error(f'unsupported protocol major version {v!r}; '
                     f'server speaks {VERSION}',
                     code=ERR_UNSUPPORTED, v=VERSION,
                     request_id=msg.get('request_id'))
    return None


def error(message: str, **extra: Any) -> Dict[str, Any]:
    out = {'ok': False, 'error': message}
    out.update(extra)
    return out


def ok(**fields: Any) -> Dict[str, Any]:
    out = {'ok': True}
    out.update(fields)
    return out
