"""Wire protocol for the warm-pool extraction service: JSON lines over a
local TCP socket.

One request per line, one response per line, UTF-8, newline-delimited —
the simplest framing that composes with ``socket.makefile`` buffering,
survives partial reads, and stays debuggable with ``nc``/``telnet``. The
endpoint binds loopback only; this is a LOCAL control surface (same
trust domain as the process), not an internet-facing API.

Commands (the ``cmd`` field):

  * ``submit``  — ``{cmd, feature_type, video_paths: [..],
    overrides: {..}, timeout_s}`` → ``{ok, request_id}`` or
    ``{ok: false, error}``. ``overrides`` merge over the server's base
    overrides and the feature YAML exactly like CLI dotlist keys.
  * ``status``  — ``{cmd, request_id}`` → per-request state + per-video
    states (see ``serve.server.Request.snapshot``).
  * ``metrics`` — ``{cmd}`` → the live metrics document
    (``docs/serving.md`` schema).
  * ``metrics_prom`` — ``{cmd}`` → ``{ok, text}``: the same state as
    Prometheus text exposition format 0.0.4 (``docs/observability.md``).
  * ``drain``   — stop admitting, finish everything queued, shut down.
  * ``ping``    — liveness probe.
"""
from __future__ import annotations

import json
from typing import Any, Dict

COMMANDS = ('submit', 'status', 'metrics', 'metrics_prom', 'drain', 'ping')

# submit() fields copied verbatim into the request (everything else in the
# message is rejected — catches client/server schema drift loudly)
SUBMIT_FIELDS = ('cmd', 'feature_type', 'video_paths', 'overrides',
                 'timeout_s')


def encode(msg: Dict[str, Any]) -> bytes:
    """One wire frame. Rejects objects whose JSON would embed a newline
    (impossible for json.dumps output, but the assert documents the
    framing invariant the reader relies on)."""
    line = json.dumps(msg, separators=(',', ':'))
    assert '\n' not in line
    return line.encode('utf-8') + b'\n'


def decode(line: bytes) -> Dict[str, Any]:
    msg = json.loads(line.decode('utf-8'))
    if not isinstance(msg, dict):
        raise ValueError('protocol messages must be JSON objects')
    return msg


def error(message: str, **extra: Any) -> Dict[str, Any]:
    out = {'ok': False, 'error': message}
    out.update(extra)
    return out


def ok(**fields: Any) -> Dict[str, Any]:
    out = {'ok': True}
    out.update(fields)
    return out
