"""Warm pool: LRU-bounded cache of live extractor workers.

The cost structure serving must hide: building an extractor transplants
weights (seconds) and the first batch through a geometry compiles an XLA
executable (more seconds). Both attach to the extractor instance — its
params live on device, its jitted step functions cache per input shape —
so keeping the INSTANCE resident keeps everything warm. The compile half
of that cost is further amortized ACROSS processes by the persistent
executable store (``aot/``): an entry built with ``aot_enabled`` loads
previously published executables at build time instead of compiling
(``builds_loaded`` vs ``builds_compiled`` in the server's pool stats),
so even a freshly booted daemon — pre-warmed via ``serve_prewarm`` —
serves its first request from resident, never-compiled-this-process
programs (docs/serving.md "Zero cold start"). The pool keys
entries by executable identity (``serve.server.pool_key``: feature_type,
model/geometry knobs, precision, device — everything that changes the
compiled program or the weights) and bounds residency with LRU eviction,
because each entry pins HBM for its params.

Eviction is GRACEFUL: an entry may have queued work, so the pool never
hard-kills — it calls ``entry.close()`` (stop accepting, drain, exit) and
hands the entry back to the caller to join. Busy entries are passed over
in favor of idle ones; if every entry is busy the pool temporarily runs
over capacity rather than stalling admission behind a drain.

Placement (:class:`DevicePlacer`): on a multi-chip host each entry is
additionally assigned a device set at build time — one chip for a
single-device extractor, N chips for a ``mesh_devices=N`` packed mesh —
chosen least-loaded so different model families spread over different
chips instead of all pinning HBM on device 0. The pool key already IS
the routing layer: a request's executable identity maps to exactly one
entry, and that entry's extractor is resident on its assigned chip(s),
so admission steers every request's windows to the silicon holding its
program.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence


class WarmPool:
    """Thread-safe LRU of serve workers with hit/miss/eviction accounting."""

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f'warm pool capacity must be >= 1: {capacity}')
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: 'OrderedDict[tuple, Any]' = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple) -> Optional[Any]:
        """The entry for ``key`` (refreshing its recency) or None. Counts
        a hit or a miss — the serve metrics hit rate is exactly this."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def peek(self, key: tuple) -> Optional[Any]:
        """Like :meth:`get` but counts nothing and touches no recency —
        for double-checked insertion after a lockless build."""
        with self._lock:
            return self._entries.get(key)

    def put(self, key: tuple, entry: Any) -> List[Any]:
        """Insert a fresh entry; returns the entries LRU-evicted to make
        room (already ``close()``d — caller joins/retires them). Only
        ``entry.idle()`` entries are evicted; when all are busy the pool
        runs over capacity until a later ``put`` finds an idle victim."""
        evicted = []
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            excess = len(self._entries) - self.capacity
            if excess > 0:
                for k in list(self._entries):
                    if excess == 0:
                        break
                    if k == key:
                        continue
                    victim = self._entries[k]
                    if victim.idle():
                        del self._entries[k]
                        self.evictions += 1
                        evicted.append(victim)
                        excess -= 1
        for victim in evicted:
            victim.close()
        if evicted:
            # structured lifecycle event: evictions explain warm-pool
            # misses and freed-HBM timing when reading logs post-hoc
            import logging

            from video_features_tpu.obs.events import event
            event(logging.INFO, 'warm pool evicted entries (LRU)',
                  subsystem='serve',
                  labels=[getattr(v, 'label', '?') for v in evicted],
                  size=len(self._entries), capacity=self.capacity)
        return evicted

    def entries(self) -> List[Any]:
        with self._lock:
            return list(self._entries.values())

    def remove(self, key: tuple, entry: Any = None) -> Optional[Any]:
        """Drop ``key`` without counting an eviction (crash retirement —
        the caller already owns closing the entry). With ``entry`` given,
        remove only if the slot still holds THAT entry: a crashed
        worker's retirement must not evict the healthy replacement a
        concurrent submit already installed under the same key."""
        with self._lock:
            current = self._entries.get(key)
            if current is None or (entry is not None
                                   and current is not entry):
                return None
            del self._entries[key]
            return current

    def pop_all(self) -> List[Any]:
        """Remove every entry (drain path); caller closes/joins them."""
        with self._lock:
            out = list(self._entries.values())
            self._entries.clear()
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                'size': len(self._entries),
                'capacity': self.capacity,
                'hits': self.hits,
                'misses': self.misses,
                'hit_rate': (self.hits / total) if total else 0.0,
                'evictions': self.evictions,
            }


class DevicePlacer:
    """Least-loaded device placement for warm-pool entries.

    Tracks how many resident entries — and how many resident BYTES —
    each local chip carries and assigns every newly built extractor the
    least-loaded chip(s) — one for a single-device entry, N for a
    ``mesh_devices=N`` packed mesh — so different model families end up
    resident on DIFFERENT chips and a multi-family server uses the whole
    host instead of stacking every params copy on device 0. Ranking is
    byte-first (entries, then device id, break ties): entries are not
    interchangeable HBM units — a bf16 fast-lane entry
    (``compute_dtype=bfloat16``) is ~half the params bytes of its fp32
    sibling and an int8 weight-lane entry (``compute_dtype=int8``,
    ops/quant.py) ~a quarter, so two bf16 entries — or four int8 ones —
    should stack on one chip before a
    second fp32 copy does. Callers that don't know their size pass 0 and
    the ranking degrades to the historical entry-count ordering. Release
    on entry retirement (eviction reap, crash) returns the chips AND the
    bytes to the free side of the ranking. Ties break by device id for
    deterministic placement; on a single-device host every assignment
    degenerates to that device (today's behavior).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._load: Dict[int, int] = {}      # jax device id → entries
        self._bytes: Dict[int, int] = {}     # jax device id → params bytes

    def assign(self, devices: Sequence, n: int, nbytes: int = 0) -> list:
        """Pick the ``n`` least-loaded of ``devices`` (all local chips of
        the extractor's platform) and count them as occupied by
        ``nbytes`` of residency EACH (params are replicated per chip on
        a mesh entry, so every chosen chip carries a full copy). ``n``
        is clamped to what exists — build-time validation
        (``configure_mesh``) already rejected genuine over-asks."""
        n = max(1, min(int(n or 1), len(devices)))
        nbytes = max(int(nbytes or 0), 0)
        with self._lock:
            ranked = sorted(devices,
                            key=lambda d: (self._bytes.get(d.id, 0),
                                           self._load.get(d.id, 0), d.id))
            chosen = ranked[:n]
            for d in chosen:
                self._load[d.id] = self._load.get(d.id, 0) + 1
                self._bytes[d.id] = self._bytes.get(d.id, 0) + nbytes
        return chosen

    def release(self, devices: Optional[Sequence],
                nbytes: int = 0) -> None:
        nbytes = max(int(nbytes or 0), 0)
        with self._lock:
            for d in devices or ():
                # keep zero counts instead of popping: the metrics mirror
                # only writes gauges for labels in snapshot(), so a popped
                # device would leave its last nonzero
                # vft_device_resident_entries reading sticky forever
                self._load[d.id] = max(self._load.get(d.id, 0) - 1, 0)
                self._bytes[d.id] = max(self._bytes.get(d.id, 0)
                                        - nbytes, 0)

    def snapshot(self) -> Dict[str, int]:
        """device id label → resident entry count (metrics surface;
        zero counts persist so a drained chip's gauge reads 0, not its
        last nonzero scrape)."""
        with self._lock:
            return {f'd{i}': c for i, c in sorted(self._load.items())}

    def snapshot_bytes(self) -> Dict[str, int]:
        """device id label → resident params bytes (the
        ``vft_device_resident_bytes`` gauges): REAL bytes, so a chip
        holding two half-size bf16 entries reads the same as one fp32
        entry — what HBM actually sees, not an entry count."""
        with self._lock:
            return {f'd{i}': b for i, b in sorted(self._bytes.items())}
