"""The warm-pool extraction service: a long-running serving daemon.

``python -m video_features_tpu serve [serve_*=.. base_override=..]``
turns the run-to-completion toolkit into a resident server: models stay
transplanted and compiled in a :class:`serve.pool.WarmPool`, and
dynamically arriving requests feed the SAME batch-major packer that PR 1
built for static worklists (``parallel/packing.py``) — windows from
concurrent requests fill shared device batches, with the per-video
fault-isolation and scatter-back contract carried over unchanged, so one
bad request never poisons a batch it shares.

Architecture (all per-process, loopback-only):

  accept thread ── JSON lines (serve/protocol.py) ── per-conn handlers
        │ submit                                        │ status/metrics
        ▼                                               ▼
  admission gate (bounded queue depth, per-request deadline)
        │ pool hit → enqueue      │ pool miss → build extractor (warm)
        ▼                         ▼
  one _Worker per warm-pool entry: a queue-fed generator streaming
  VideoTasks (+ FLUSH on arrival lulls) into ``run_packed``, which never
  returns until the worker drains — requests arriving while the device
  runs batch k pack into batch k+1.

Graceful drain (SIGTERM / ``drain`` command): admission closes, every
worker's feed ends after its queued videos, ``run_packed`` flushes its
tail pools and finalizes every started video, then the process exits —
no completed request's output is ever lost, and interrupted videos
re-extract on restart via the unchanged resume contract.
"""
from __future__ import annotations

import itertools
import logging
import os
import queue
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from video_features_tpu.config import (
    OBS_DEFAULTS, Config, knob_exclude, load_config, split_serve_config,
)
from video_features_tpu.obs.context import accept_traceparent
from video_features_tpu.obs.events import event
from video_features_tpu.parallel.packing import FLUSH, VideoTask
from video_features_tpu.registry import (
    LIVE_FEATURES, PACKED_FEATURES, create_extractor,
)
from video_features_tpu.serve import metrics as metrics_mod
from video_features_tpu.serve import protocol
from video_features_tpu.serve.pool import DevicePlacer, WarmPool

_CLOSE = object()

# terminal requests retained for status() queries; older ones age out so a
# week-long daemon's request table stays bounded (same reasoning as
# metrics.LATENCY_WINDOW)
REQUEST_HISTORY = 4096

# per-recorder span bound for the /trace assembly: the route reads the
# RECENT window of each ring, never the full 200K events under the
# recorder lock on a request path
TRACE_ROUTE_SPAN_LIMIT = 50_000

# config keys that do NOT change the compiled program, the weights, or
# the worker's run behavior — everything else lands in the pool key.
# The per-knob classification (and its rationale: why tmp_path, the
# cache_* namespace, and mesh_devices stay IN the key while trace/
# inflight/farm knobs share the FIRST builder's settings) lives in ONE
# place, ``config.KNOB_CLASSIFICATION`` — the cache fingerprint derives
# its own exclusion set from the same registry, and vft-lint rejects
# hand-maintained copies of either list.
_KEY_EXCLUDE = knob_exclude('pool_key')


def pool_key(args: Config) -> tuple:
    """Executable identity of a sanity-checked request config."""
    return tuple(sorted((k, repr(v)) for k, v in args.items()
                        if k not in _KEY_EXCLUDE))


def resolve_mesh_devices(args: Config) -> Config:
    """Resolve ``mesh_devices=0`` (auto-detect) to the explicit local
    device count IN PLACE, before ``pool_key`` runs: 0 and the
    equivalent explicit width must share one warm entry — keying on the
    raw 0 would build (and place) a duplicate of the identical sharded
    program. Same resolution ``configure_mesh`` applies at build time,
    just early enough for routing."""
    n = args.get('mesh_devices', 1)
    if n is not None and int(n) == 0:
        from video_features_tpu.utils.device import jax_devices_all
        args['mesh_devices'] = len(jax_devices_all(
            args.get('device', 'cpu')))
    return args


class _ServeTask(VideoTask):
    """A packed-scheduler task carrying its originating request. Each
    task gets its own child span under the request's trace, so the
    merged timeline distinguishes per-video work inside one request."""

    __slots__ = ('request',)

    def __init__(self, path: str, request: 'Request',
                 out_root: str, segment=None) -> None:
        super().__init__(path, out_root=out_root, segment=segment,
                         trace=(request.trace.child()
                                if request.trace is not None else None))
        self.request = request


class _LiveServeTask(_ServeTask):
    """One live session's task: windows come from the session's
    network-fed windower (``windows_override``), every scattered row
    streams back through ``on_window``, and nothing is saved or cached
    (``stream_only``) — the chunked response IS the output."""

    __slots__ = ('session',)

    ephemeral = True          # no file behind it: skip resume/cache
    stream_only = True        # rows stream out; never accumulate/save

    def __init__(self, path: str, request: 'Request', out_root: str,
                 session) -> None:
        super().__init__(path, request, out_root)
        self.session = session

    def windows_override(self, ex):
        return self.session.windows(ex)

    def on_window(self, feats: Dict[str, Any], meta) -> None:
        self.session.send_window(feats, meta)


class Request:
    """Admission-to-completion state for one submit."""

    def __init__(self, request_id: str, feature_type: str, paths: List[str],
                 deadline: Optional[float],
                 segment: Optional[tuple] = None,
                 priority: str = 'interactive',
                 trace=None) -> None:
        self.id = request_id
        self.feature_type = feature_type
        self.videos: Dict[str, str] = {p: 'pending' for p in paths}
        self.pending = len(paths)
        self.deadline = deadline          # monotonic, None = no deadline
        self.segment = segment            # (start_s, end_s) | None
        self.priority = priority
        # request-scoped trace context (obs/context.TraceContext):
        # accepted from the caller's traceparent or minted at admission;
        # every task span derives a child from it
        self.trace = trace
        self.t0 = time.monotonic()
        self.done_t: Optional[float] = None

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline

    def state(self) -> str:
        if self.pending > 0:
            return 'running'
        states = set(self.videos.values())
        if states <= {'saved', 'skipped', 'cached'}:
            return 'done'
        if states & {'saved', 'skipped', 'cached'}:
            return 'partial'
        return 'failed'

    def snapshot(self) -> Dict[str, Any]:
        out = {'request_id': self.id, 'state': self.state(),
               'feature_type': self.feature_type,
               'videos': dict(self.videos)}
        if self.trace is not None:
            out['trace_id'] = self.trace.trace_id
        if self.segment is not None:
            out['range'] = [float(self.segment[0]), float(self.segment[1])]
        if self.priority != 'interactive':
            out['priority'] = self.priority
        if self.done_t is not None:
            out['latency_s'] = round(self.done_t - self.t0, 4)
        return out


class FusedRequest(Request):
    """Umbrella for one ``features=[...]`` submit: the caller holds ONE
    request id while per-family children run through the normal
    admission/worker machinery (each family its own warm-pool entry,
    cache, deadline, and fault isolation). The umbrella is terminal
    when every child is; its state aggregates the children's. It never
    occupies an admission slot itself and never bumps the completed/
    failed counters (the children already did) — its one completion
    side effect is firing the completion listeners, which is where the
    ingress gateway releases the request's tenant quota unit."""

    def __init__(self, request_id: str, features: List[str],
                 paths: List[str], priority: str = 'interactive',
                 trace=None) -> None:
        super().__init__(request_id, '+'.join(features), paths, None,
                         priority=priority, trace=trace)
        self.features = list(features)
        self.children: Dict[str, Request] = {}
        self.pending = 0        # completion is tracked via the children

    def state(self) -> str:
        if not self.children:
            return 'running'    # fan-out still in flight
        states = {c.state() for c in self.children.values()}
        if 'running' in states or any(c.done_t is None
                                      for c in self.children.values()):
            return 'running'
        if states == {'done'}:
            return 'done'
        if states & {'done', 'partial'}:
            return 'partial'
        return 'failed'

    def snapshot(self) -> Dict[str, Any]:
        out = {'request_id': self.id, 'state': self.state(),
               'feature_type': self.feature_type,
               'features': list(self.features),
               # per-family child request ids + video states: a fused
               # status answer is the N family answers, keyed
               'requests': {f: c.id for f, c in self.children.items()},
               'videos': {f: dict(c.videos)
                          for f, c in self.children.items()}}
        if self.trace is not None:
            out['trace_id'] = self.trace.trace_id
        if self.priority != 'interactive':
            out['priority'] = self.priority
        if self.done_t is not None:
            out['latency_s'] = round(self.done_t - self.t0, 4)
        return out


_WD_SEQ = itertools.count(1)


class _Worker:
    """One warm-pool entry: an extractor + the thread that drives one
    long-lived ``run_packed`` over a queue-fed task stream."""

    def __init__(self, server: 'ExtractionServer', key: tuple, label: str,
                 extractor, idle_flush_s: float,
                 max_batch_wait_s: float = 2.0) -> None:
        self.server = server
        self.key = key
        self.label = label
        # watchdog ledger key: labels COLLIDE across pool entries (two
        # entries for one family with different overrides — metrics()
        # disambiguates the same collision with '#i'), and a shared row
        # would let worker B's advances mask worker A's stall and a
        # retirement delete a live sibling's state — so every worker
        # gets a process-unique key (itertools.count: atomic, no lock)
        self.wd_key = f'{label}#{next(_WD_SEQ)}'
        self.ex = extractor
        self.idle_flush_s = idle_flush_s
        self.max_batch_wait_s = max_batch_wait_s
        self.queue: 'queue.Queue' = queue.Queue()
        # chips this entry's extractor is resident on (DevicePlacer
        # assignment; None after release so retirement is idempotent)
        self.devices: Optional[List] = None
        self.outstanding: set = set()
        self._lock = threading.Lock()
        self.closed = False
        self.crashed = False
        self.thread = threading.Thread(
            target=self._run, name=f'serve-worker-{label}', daemon=True)

    def start(self) -> None:
        self.thread.start()

    def submit(self, tasks: List[_ServeTask]) -> None:
        with self._lock:
            self.outstanding.update(tasks)
        self.server._wd_pending(self)
        for t in tasks:
            self.queue.put(t)
        if self.crashed:
            # lost the race with a crash mid-submit: the crash handler may
            # have already swept outstanding — fail whatever it missed so
            # no request hangs
            with self._lock:
                stranded = [t for t in tasks if t in self.outstanding]
                for t in stranded:
                    self.outstanding.discard(t)
            for t in stranded:
                t.failed = True
                self.server._video_done(t)

    def idle(self) -> bool:
        with self._lock:
            return not self.outstanding

    def close(self) -> None:
        """Stop accepting; the feed ends after everything already queued."""
        self.closed = True
        self.queue.put(_CLOSE)

    def _feed(self):
        """Blocking task stream for ``run_packed``: yields queued tasks,
        skips videos whose request deadline already passed, and emits
        FLUSH (a) after each arrival burst — pooled windows never wait on
        future traffic — and (b) at least every ``max_batch_wait_s``
        between tasks. The primary continuous-traffic liveness bound is
        ``packed_batches``' pool aging (it fires on every flowing
        window, mid-video included); this feed-level timer covers the
        complement where tasks flow but windows don't (e.g. a run of
        resume-skip requests while an odd-geometry window sits pooled)."""
        dirty = False
        last_flush = time.monotonic()
        while True:
            was_idle = not dirty
            try:
                item = self.queue.get(
                    timeout=self.idle_flush_s if dirty else None)
            except queue.Empty:
                dirty = False
                last_flush = time.monotonic()
                yield FLUSH
                continue
            if item is _CLOSE:
                return
            task = item
            if task.request.expired():
                with self._lock:
                    self.outstanding.discard(task)
                # republish the watchdog ledger: an all-expired backlog
                # must read as pending=0, not as a stalled worker
                self.server._wd_pending(self)
                self.server._video_expired(task)
                continue
            if was_idle:
                # the blocking wait just ended inside the scheduler's
                # next(); yielding FLUSH first pins that idle span on the
                # queue_idle stage instead of this task's decode time
                # (no-op for the empty pools)
                last_flush = time.monotonic()
                yield FLUSH
            elif time.monotonic() - last_flush >= self.max_batch_wait_s:
                last_flush = time.monotonic()
                yield FLUSH
            dirty = True
            yield task

    def _on_video_done(self, task) -> None:
        with self._lock:
            self.outstanding.discard(task)
        self.server._wd_pending(self)
        self.server._video_done(task)

    def _run(self) -> None:
        try:
            try:
                self.ex.extract_packed(self._feed(),
                                       on_video_done=self._on_video_done,
                                       max_pool_age_s=self.max_batch_wait_s)
            finally:
                # publish this entry's telemetry artifacts (trace_out /
                # manifest_out) on drain/crash; no-op without the knobs,
                # never raises. When the server owns a merged export of
                # the same trace path, the per-worker export is skipped
                # — a worker outliving the drain grace period must not
                # clobber the merged trace with its single-recorder view
                shared = self.server.base_overrides.get('trace_out')
                self.ex.finish_obs(export_trace=(
                    shared is None or str(shared) != self.ex.trace_out))
        except Exception:
            # scheduler-level crash (bugs, OOM — NOT per-video faults,
            # which run_packed isolates): fail everything outstanding so
            # no request hangs, and retire this entry so the next submit
            # rebuilds a healthy one
            self.crashed = True
            event(logging.ERROR, 'serve worker crashed; failing its '
                  'outstanding videos and retiring the entry',
                  subsystem='serve', exc_info=True, label=self.label)
            with self._lock:
                stranded = list(self.outstanding)
                self.outstanding.clear()
            for task in stranded:
                task.failed = True
                self.server._video_done(task)
            self.server._retire_crashed(self)
            # post-mortem bundle AFTER the stranded videos failed and
            # the entry retired — the dump is telemetry, the recovery
            # above is the contract; never raises, off the hot path
            self.server._dump_blackbox('serve_worker_crash',
                                       label=self.label,
                                       stranded=len(stranded))


class ExtractionServer:
    """Resident extraction daemon + its loopback JSON-lines endpoint."""

    def __init__(self,
                 base_overrides: Optional[Dict[str, Any]] = None,
                 host: str = '127.0.0.1',
                 port: int = 0,
                 queue_depth: int = 64,
                 pool_size: int = 4,
                 idle_flush_s: float = 0.05,
                 max_batch_wait_s: float = 2.0,
                 default_timeout_s: Optional[float] = None,
                 metrics_path: Optional[str] = None,
                 batch_shed_fraction: float = 0.5) -> None:
        self.base_overrides = dict(base_overrides or {})
        self.host, self._port_req = host, port
        self.queue_depth = queue_depth
        self.idle_flush_s = idle_flush_s
        self.max_batch_wait_s = max_batch_wait_s
        self.default_timeout_s = default_timeout_s
        self.metrics_path = metrics_path
        # priority-class admission: 'batch' requests only see this
        # fraction of the queue, so a saturated queue sheds batch first
        # and keeps headroom for interactive traffic
        self.batch_shed_fraction = float(batch_shed_fraction)
        self._batch_capacity = max(
            1, int(queue_depth * self.batch_shed_fraction))
        # the network front door (ingress/), when enabled: attached via
        # attach_ingress so drain can stop it (reap half-open
        # connections, end live sessions) in the right order
        self.ingress = None
        # fired (with the terminal Request) after every completion —
        # the ingress gateway releases per-tenant concurrency here
        self.completion_listeners: List = []

        self.pool = WarmPool(pool_size)
        # placement-aware residency: every built entry gets the
        # least-loaded local chip(s) — one for a single-device config, N
        # for mesh_devices=N — so different families land on different
        # silicon; the pool-key lookup then routes each request's windows
        # to the chip(s) holding its executable
        self._placer = DevicePlacer()
        # one registry per server instance (obs.metrics): counters + the
        # latency histogram live here; prometheus_text mirrors the
        # point-in-time document values into gauges on the same registry
        from video_features_tpu.obs.metrics import MetricsRegistry
        self.registry = MetricsRegistry()
        self.stats = metrics_mod.RequestStats(self.registry)
        # prometheus_text sets shared gauges then renders; concurrent
        # scrape + mirror writes must not interleave two documents'
        # values into one exposition
        self._prom_lock = threading.Lock()
        self._started_at = time.monotonic()
        # one coarse lock serializes admission + request-state mutation;
        # the hot path (device batches) never takes it
        self._lock = threading.RLock()
        self._requests: Dict[str, Request] = {}
        self._done_ids: 'deque[str]' = deque()   # completion order, bounded
        self._inflight_videos = 0
        self._next_id = 0
        # per-key build serialization: N concurrent cold submits for one
        # config must transplant ONCE, not N times (the latecomers wait,
        # then adopt the winner's warm worker)
        self._build_locks: Dict[tuple, threading.Lock] = {}
        # entry builds split by which path their programs took (vft-aot):
        # an entry whose AOT warm LOADED every program from the
        # persistent executable store counts as builds_loaded; anything
        # that compiled (or has no store) counts as builds_compiled —
        # 'second boot is compile-free' is literally
        # builds_compiled == 0 on these counters
        self._builds_compiled = 0
        self._builds_loaded = 0
        # content-addressed feature caches touched by requests, keyed by
        # cache dir — metrics merges their hit/miss/bytes-saved counters
        # alongside the warm-pool hit rate
        self._caches: Dict[str, Any] = {}
        self._retired: List[_Worker] = []
        # ONE merged stage report accumulates every retired/crashed
        # entry's history — per-entry retention would grow (and bloat
        # every metrics document) linearly with lifetime eviction count
        self._retired_stages: Dict[str, Dict] = {}
        # every worker's span recorder (when obs is configured), for the
        # merged drain export; bounded like the ring buffers themselves
        # so lifetime churn can't grow it without limit
        self._trace_recorders: 'deque' = deque(maxlen=32)
        # LONG-LIVED recorders (the server's own admission-span recorder,
        # the ingress gateway's) live OUTSIDE the churn deque: >32 warm
        # builds over a daemon's lifetime must age out old WORKER
        # recorders, never the admission/ingress spans every /trace
        # assembly, drain export, and black-box bundle depends on
        self._persistent_recorders: List = []
        # the server's own recorder (admission spans + /trace assembly),
        # present only when the base trace_out is configured — same
        # gating as the workers' recorders
        self._server_recorder = None
        if self.base_overrides.get('trace_out'):
            from video_features_tpu.obs.spans import SpanRecorder
            self._server_recorder = SpanRecorder()
            self._persistent_recorders.append(self._server_recorder)
        # vft-flight: crash-dump black box (postmortem_dir base
        # override) + stall watchdog (watchdog_stall_s). Both are
        # telemetry: absent knobs = exactly today's behavior.
        self.blackbox = None
        if self.base_overrides.get('postmortem_dir'):
            from video_features_tpu.obs.blackbox import BlackBox
            max_bytes = self.base_overrides.get('postmortem_max_bytes')
            self.blackbox = BlackBox(
                str(self.base_overrides['postmortem_dir']),
                max_bytes=(int(max_bytes) if max_bytes is not None
                           else OBS_DEFAULTS['postmortem_max_bytes']),
                recorders=self._all_recorders,
                metrics_fn=self._metrics_for_blackbox,
                prom_fn=lambda: self._prometheus(
                    self._metrics_for_blackbox()))
        self.watchdog = None
        if self.base_overrides.get('watchdog_stall_s'):
            from video_features_tpu.obs.watchdog import StallWatchdog
            self.watchdog = StallWatchdog(
                float(self.base_overrides['watchdog_stall_s']),
                on_stall=self._on_stall,
                registry=self.registry).start()
        # vft-scope: SLO burn-rate evaluation over this server's own
        # request families (slo_latency_p99_s / slo_availability base
        # overrides). Ticks ride metrics assembly — no extra thread.
        self.slo = None
        if self.base_overrides.get('slo_latency_p99_s') is not None \
                or self.base_overrides.get('slo_availability') is not None:
            from video_features_tpu.obs.slo import SloEvaluator
            _lat = self.base_overrides.get('slo_latency_p99_s')
            _avail = self.base_overrides.get('slo_availability')
            self.slo = SloEvaluator(
                self.registry,
                latency_p99_s=(float(_lat) if _lat is not None else None),
                availability=(float(_avail) if _avail is not None
                              else None))
        # feature index (index_enabled base override): ingest worker +
        # query engine behind the search/index_status commands and the
        # ingress /v1/search route. Created AFTER the watchdog so its
        # ingest row can register; its thread starts with the server.
        self.index_service = None
        if self.base_overrides.get('index_enabled'):
            from video_features_tpu.index.service import IndexService
            self.index_service = IndexService(self, self.base_overrides)
        self._draining = False
        self._drained = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._sock is not None, 'server not started'
        return self._sock.getsockname()[1]

    def start(self) -> 'ExtractionServer':
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._port_req))
        self._sock.listen(16)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name='serve-accept', daemon=True)
        self._accept_thread.start()
        if self.index_service is not None:
            self.index_service.start()
        return self

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain (daemon entry point only — in a
        test/library context the caller drives ``drain()`` itself)."""
        def _on_signal(signum, frame):
            print(f'serve: signal {signum} — draining', file=sys.stderr)
            self.drain(wait=False)
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def serve_forever(self) -> None:
        self._drained.wait()

    def drain(self, wait: bool = True, grace_s: float = 300.0) -> None:
        """Graceful shutdown: close admission, let every worker finish its
        queued videos (tail pools flush padded), then stop the endpoint.
        Idempotent; ``wait=False`` returns immediately and finishes on a
        background thread (the signal-handler path)."""
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            if wait:
                self._drained.wait(grace_s)
            return
        if self.ingress is not None:
            # FIRST: stop accepting network traffic and end every live
            # session's frame input, so the workers' feeds can actually
            # drain (a live task otherwise blocks on future frames)
            try:
                self.ingress.begin_drain()
            except Exception:
                # drain continues regardless, but a front door that
                # failed to close is worth a line in the log
                event(logging.WARNING, 'ingress begin_drain failed',
                      subsystem='serve', exc_info=True)
        with self._lock:
            # snapshot under the lock: _reap_retired_locked mutates
            # _retired concurrently
            workers = self.pool.pop_all() + list(self._retired)
        for w in workers:
            w.close()

        def _finish():
            deadline = time.monotonic() + grace_s
            pending = workers
            while pending:
                for w in pending:
                    if w.thread.is_alive():
                        w.thread.join(max(0.0, deadline - time.monotonic()))
                    # the drain's final metrics document must show the
                    # chips freed, not the pre-drain residency (idempotent
                    # with the reap/crash release paths)
                    self._release_placement(w)
                # re-sweep: a cold submit racing the drain may have
                # inserted a freshly built worker after the first
                # pop_all snapshot
                with self._lock:
                    pending = self.pool.pop_all()
                for w in pending:
                    w.close()
                if time.monotonic() >= deadline:
                    break
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            if self.ingress is not None:
                # LAST: force-close whatever connections are still open
                # (half-open clients that never finished their request
                # must not pin handler threads past the drain)
                try:
                    self.ingress.finish_drain()
                except Exception:
                    event(logging.WARNING, 'ingress finish_drain failed',
                          subsystem='serve', exc_info=True)
            if self.index_service is not None:
                # stop the ingest worker before the watchdog goes down
                # (its ledger row is forgotten here) and before the
                # final metrics/trace exports, so they carry the index's
                # terminal state
                self.index_service.stop()
            if self.watchdog is not None:
                # stop BEFORE the final exports: a drain-quiesced worker
                # with close-sentinel queue state must not read as a
                # stall while the monitor races shutdown
                self.watchdog.stop()
            doc = self.metrics()
            metrics_mod.write_metrics_file(self.metrics_path, doc,
                                           prom_text=self._prometheus(doc))
            self._export_merged_trace()
            self._drained.set()

        if wait:
            _finish()
        else:
            threading.Thread(target=_finish, name='serve-drain',
                             daemon=True).start()

    @property
    def drained(self) -> bool:
        return self._drained.is_set()

    def _prometheus(self, doc: Dict[str, Any]) -> str:
        """One atomic mirror-gauges-and-render pass (see _prom_lock)."""
        with self._prom_lock:
            return metrics_mod.prometheus_text(doc, self.registry)

    def _export_merged_trace(self) -> None:
        """Stitch EVERY worker's span recorder into one Chrome trace at
        the server-wide ``trace_out`` base override (drain path, after
        the workers joined — so this merged write lands after, and
        supersedes, each worker's own single-recorder export to the
        shared path). Per-request trace_out paths keep the per-worker
        exports. Never raises."""
        path = self.base_overrides.get('trace_out')
        if not path:
            return
        recorders = self._all_recorders()
        if not recorders:
            return
        try:
            from video_features_tpu.obs.spans import export_merged
            export_merged(recorders, str(path))
        except Exception:
            import logging

            from video_features_tpu.obs.events import event
            event(logging.WARNING, 'merged trace export failed',
                  subsystem='serve', exc_info=True, path=str(path))

    # -- vft-flight: watchdog + black box ------------------------------------

    def _all_recorders(self) -> List:
        """Every live span recorder: the long-lived server/ingress ones
        plus the (bounded, churn-evicted) worker recorders."""
        with self._lock:
            return (list(self._persistent_recorders)
                    + list(self._trace_recorders))

    def _wd_pending(self, worker: '_Worker') -> None:
        """Mirror a worker's outstanding-task count into the watchdog
        ledger (no-op without a watchdog)."""
        if self.watchdog is None:
            return
        # set_pending runs UNDER the worker lock: reading the count and
        # publishing it must be one atomic step, or a concurrent
        # submit/done pair can land their publishes out of order and
        # leave the ledger at a value outstanding never had (stale >0 =
        # spurious stall; stale 0 = masked wedge). Safe nesting: the
        # watchdog's own lock is a leaf — nothing inside it ever takes
        # a worker lock.
        with worker._lock:
            self.watchdog.set_pending(worker.wd_key,
                                      len(worker.outstanding))

    def _wire_watchdog(self, worker: '_Worker') -> None:
        """Feed the watchdog's progress ledger from the worker's tracer
        — the SAME instrumentation sites as the stage table/timeline.
        Farm decode workers get their own sub-rows (``label/farm-wN``)
        via the ``worker=`` span attr the farm already stamps."""
        if self.watchdog is None:
            return
        from video_features_tpu.utils.tracing import NULL_TRACER, Tracer
        if worker.ex.tracer is NULL_TRACER or not worker.ex.tracer.enabled:
            # serve forces profile=True at admission so this never fires
            # on the normal path — but a disabled tracer would mean an
            # armed watchdog with pending work and NO advances (every
            # busy worker reads as stalled), and hooking the shared
            # NULL_TRACER singleton would leak across extractors
            worker.ex.tracer = Tracer(enabled=True)
        wd, wd_key = self.watchdog, worker.wd_key

        def _progress(stage: str, farm_worker=None) -> None:
            wd.advance(wd_key, stage)
            if farm_worker is not None:
                wd.advance(f'{wd_key}/farm-w{farm_worker}', stage)

        worker.ex.tracer.progress = _progress
        # farm decode workers' QUEUED work: the farm mirrors each
        # worker's assignment backlog on its supervise tick, so a single
        # wedged farm worker trips its own row even while siblings keep
        # the serve-level row advancing
        worker.ex.watchdog_pending = (
            lambda widx, n: wd.set_pending(f'{wd_key}/farm-w{widx}',
                                           int(n)))

    def _wd_forget(self, worker: '_Worker') -> None:
        if self.watchdog is not None:
            self.watchdog.forget(worker.wd_key)
            # farm sub-rows retire with their serve worker
            self.watchdog.forget_prefix(worker.wd_key + '/')

    def _on_stall(self, info: Dict[str, Any]) -> None:
        """Watchdog trip: the structured event + counter already fired
        (obs/watchdog.py); the server's contribution is the post-mortem
        bundle."""
        self._dump_blackbox('watchdog_stall', **info)

    def _dump_blackbox(self, reason: str, **extra: Any) -> None:
        """Write a post-mortem bundle (no-op without postmortem_dir;
        never raises; never on the request hot path — callers are crash
        handlers and the watchdog monitor thread)."""
        if self.blackbox is None:
            return
        if self.watchdog is not None:
            extra.setdefault('watchdog', self.watchdog.snapshot())
        self.blackbox.dump(reason, **extra)

    def _metrics_for_blackbox(self) -> Dict[str, Any]:
        """The metrics document for a dump — with a lock PROBE first: a
        dump often documents a wedge, and if the admission lock is what
        wedged, the bundle must skip this section rather than hang on
        it (BlackBox treats the raise as a best-effort section miss)."""
        if not self._lock.acquire(timeout=2.0):
            raise RuntimeError(
                'admission lock unavailable; skipping metrics section')
        self._lock.release()
        return self.metrics()

    def _record_admission(self, t0: float, req: Request,
                          **attrs: Any) -> None:
        """The 'admission' span: submit-call wall time under the
        request's trace (server recorder; present only with a base
        trace_out, like every other recorder)."""
        rec = self._server_recorder
        if rec is None:
            return
        rec.span('admission', t0, time.perf_counter(),
                 request_id=req.id, feature_type=req.feature_type,
                 priority=req.priority,
                 **(req.trace.attrs() if req.trace is not None else {}),
                 **attrs)

    def request_trace(self, request_id: str) -> Dict[str, Any]:
        """One request's assembled span timeline: every event across the
        live recorders (workers, ingress, the server's own admission
        spans) carrying the request's trace_id — directly
        (``trace_id``), as a shared-batch member (``trace_ids``), or by
        ``request_id``. Bounded per recorder (TRACE_ROUTE_SPAN_LIMIT);
        events older than the rings have wrapped out (flight-recorder
        semantics, same as the export)."""
        with self._lock:
            req = self._requests.get(request_id)
        recorders = self._all_recorders()
        if req is None:
            return protocol.error(f'unknown request_id {request_id!r}',
                                  code=protocol.ERR_NOT_FOUND)
        ctx = req.trace
        trace_id = ctx.trace_id if ctx is not None else None
        events: List[Dict[str, Any]] = []
        if recorders and trace_id is not None:
            origin = min(r.origin() for r in recorders)
            for rec in recorders:
                for e in rec.snapshot(origin=origin,
                                      limit=TRACE_ROUTE_SPAN_LIMIT):
                    if e.get('ph') == 'M':
                        continue
                    args = e.get('args') or {}
                    if args.get('trace_id') == trace_id \
                            or trace_id in (args.get('trace_ids') or ()) \
                            or args.get('request_id') == request_id:
                        events.append(e)
            events.sort(key=lambda e: e['ts'])
        return protocol.ok(request_id=request_id, trace_id=trace_id,
                           state=req.state(), events=events)

    # -- admission + dispatch ------------------------------------------------

    def _admission_capacity(self, priority: str) -> int:
        """The queue capacity this priority class sees: interactive gets
        the full depth, batch only ``batch_shed_fraction`` of it — so
        under saturation batch is shed first and never starves
        interactive headroom. A shed submit is REJECTED before any
        accounting: it never occupies an admission slot."""
        return (self._batch_capacity if priority == 'batch'
                else self.queue_depth)

    @staticmethod
    def _check_range(range_s) -> Optional[tuple]:
        """Validated (start_s, end_s) segment, or raises ValueError."""
        if range_s is None:
            return None
        if not isinstance(range_s, (list, tuple)) or len(range_s) != 2:
            raise ValueError('range must be [start_s, end_s]')
        import math
        if not all(math.isfinite(float(v)) for v in range_s):
            # JSON happily parses 1e999 → inf, which would sail through
            # the ordering check below and blow up as an OverflowError
            # deep in the decode thread instead of a structured reject
            raise ValueError(f'range values must be finite; got {range_s}')
        # millisecond quantization up front (same as VideoTask's): the
        # wire value, the frame filter, the output name, and the cache
        # key must all agree on ONE range
        start_s = round(float(range_s[0]), 3)
        end_s = round(float(range_s[1]), 3)
        if not (0 <= start_s < end_s):
            raise ValueError(
                f'range must satisfy 0 <= start < end (at millisecond '
                f'resolution); got {range_s}')
        return (start_s, end_s)

    def submit(self, feature_type: str, video_paths: List[str],
               overrides: Optional[Dict[str, Any]] = None,
               timeout_s: Optional[float] = None,
               range_s=None,
               priority: str = 'interactive',
               traceparent: Optional[str] = None,
               features: Optional[List[str]] = None,
               _live_session=None) -> Dict[str, Any]:
        if features is not None and _live_session is None:
            # fused multi-family submit: one request id, per-family
            # children through the normal machinery (feature_type is
            # ignored when features is given — the list IS the spec)
            return self._submit_fused(
                features, video_paths, overrides=overrides,
                timeout_s=timeout_s, range_s=range_s, priority=priority,
                traceparent=traceparent)
        # request-scoped trace context: adopt the caller's W3C
        # traceparent or mint one — minted EARLY so even the admission
        # span of a rejected submit has an identity to hang on
        t0_admit = time.perf_counter()
        trace_ctx = accept_traceparent(traceparent)
        if not isinstance(video_paths, (list, tuple)) or not video_paths:
            self.stats.bump('rejected')
            return protocol.error('video_paths must be a non-empty list',
                                  code=protocol.ERR_INVALID)
        if priority is None:
            priority = 'interactive'
        if priority not in protocol.PRIORITIES:
            self.stats.bump('rejected')
            return protocol.error(
                f'unknown priority {priority!r}; known: '
                f'{", ".join(protocol.PRIORITIES)}',
                code=protocol.ERR_INVALID)
        try:
            segment = self._check_range(range_s)
        except (TypeError, ValueError) as e:
            self.stats.bump('rejected')
            return protocol.error(f'invalid range: {e}',
                                  code=protocol.ERR_INVALID)
        paths = [str(p) for p in video_paths]
        if len(set(paths)) != len(paths):
            # Request.videos is keyed by path: a duplicate would collapse
            # there and the request could never complete. (sanity_check's
            # unique-stem assert also catches this, but asserts vanish
            # under `python -O` — this check must not.)
            self.stats.bump('rejected')
            return protocol.error('duplicate video_paths in one request',
                                  code=protocol.ERR_INVALID)
        if feature_type not in PACKED_FEATURES:
            self.stats.bump('rejected')
            return protocol.error(
                f'feature_type {feature_type!r} has no packed/serving '
                f'support; serveable: {", ".join(sorted(PACKED_FEATURES))}',
                code=protocol.ERR_UNSUPPORTED)
        if _live_session is not None and feature_type not in LIVE_FEATURES:
            self.stats.bump('rejected')
            return protocol.error(
                f'feature_type {feature_type!r} has no live-session '
                f'support; live-capable: {", ".join(sorted(LIVE_FEATURES))}',
                code=protocol.ERR_UNSUPPORTED)
        # config resolution is LOCK-FREE: the YAML read + sanity_check
        # must not stall completion callbacks or status/metrics — the
        # admission lock guards only server state (the block below)
        try:
            args, key = self._resolve_entry_config(feature_type, paths,
                                                   overrides)
        except Exception as e:
            self.stats.bump('rejected')
            return protocol.error(f'invalid request: {e}',
                                  code=protocol.ERR_INVALID)

        # -- content-addressed cache: answer hits BEFORE admission -------
        # A hit is an O(read) file copy — it must not occupy a queue slot
        # (admission capacity is for decode+inference work), must not wake
        # a worker, and is answered even when the queue is full. Lookup
        # failures (unreadable video, broken cache dir) degrade to misses
        # and take the normal extraction path, where the standard
        # per-video fault isolation reports them.
        cache_hits: List[str] = []
        if args.get('cache_enabled') and not self._draining \
                and _live_session is None:
            cache_hits = self._answer_cache_hits(args, paths, segment)
            if cache_hits:
                self.stats.bump('cached_videos', len(cache_hits))
        miss_paths = ([p for p in paths if p not in set(cache_hits)]
                      if cache_hits else paths)
        if not miss_paths:
            # the whole request was served from cache: terminal at birth
            with self._lock:
                self._next_id += 1
                req = Request(f'r{self._next_id:06d}', feature_type, paths,
                              None, segment=segment, priority=priority,
                              trace=trace_ctx)
                for p in paths:
                    req.videos[p] = 'cached'
                req.pending = 0
                self._requests[req.id] = req
                self._record_done_locked(req)
            self.stats.bump('submitted')
            self._record_admission(t0_admit, req, cached=len(paths))
            self._after_completion(req)
            return protocol.ok(request_id=req.id,
                               trace_id=trace_ctx.trace_id)

        with self._lock:
            if self._draining:
                self.stats.bump('rejected')
                return protocol.error('draining', code=protocol.ERR_SHED)
            capacity = self._admission_capacity(priority)
            if self._inflight_videos + len(miss_paths) > capacity:
                self.stats.bump('rejected')
                return protocol.error(
                    'queue_full', code=protocol.ERR_SHED,
                    depth=self._inflight_videos,
                    capacity=capacity, priority=priority)
            worker = self.pool.get(key)
            build_lock = self._build_locks.setdefault(
                key, threading.Lock())

        # bounded retry: a just-acquired worker can in principle be LRU-
        # evicted (it is idle until we enqueue) between acquisition and
        # admission — enqueueing behind its _CLOSE sentinel would strand
        # the tasks, so re-acquire instead
        for _ in range(5):
            if worker is None or worker.closed or worker.crashed:
                # the cold-start cost serving exists to amortize:
                # transplant here, compile on the first batch — both
                # attached to this entry for its whole residency.
                # Deliberately OUTSIDE the admission lock (a multi-second
                # build must not stall warm workers' completions or
                # status/metrics calls) but UNDER the per-key build lock
                # (N concurrent cold submits transplant once — the losers
                # block here, then adopt the winner's).
                with build_lock:
                    existing = self.pool.peek(key)
                    if existing is not None and not (existing.closed
                                                     or existing.crashed):
                        worker = existing
                    else:
                        try:
                            worker = self._spawn_worker(args, key)
                        except Exception as e:
                            self.stats.bump('rejected')
                            return protocol.error(
                                f'extractor build failed: {e}',
                                code=protocol.ERR_INTERNAL)

            with self._lock:
                if self._draining:
                    # drain may have swept the pool before our (possibly
                    # just-built) worker landed in it — close it too, so
                    # a late insert can't outlive the drain (graceful:
                    # close never drops already-enqueued work)
                    worker.close()
                    self.stats.bump('rejected')
                    return protocol.error('draining',
                                          code=protocol.ERR_SHED)
                if self._inflight_videos + len(miss_paths) > \
                        self._admission_capacity(priority):
                    # re-check after the lockless build window; the
                    # freshly built worker stays pooled, warm for the
                    # caller's retry
                    self.stats.bump('rejected')
                    return protocol.error(
                        'queue_full', code=protocol.ERR_SHED,
                        depth=self._inflight_videos,
                        capacity=self._admission_capacity(priority),
                        priority=priority)
                if worker.closed or worker.crashed:
                    worker = None         # evicted/crashed in the window
                    continue
                self._reap_retired_locked()

                if timeout_s is None:
                    timeout_s = self.default_timeout_s
                deadline = (time.monotonic() + float(timeout_s)
                            if timeout_s is not None else None)
                self._next_id += 1
                req = Request(f'r{self._next_id:06d}', feature_type, paths,
                              deadline, segment=segment, priority=priority,
                              trace=trace_ctx)
                for p in cache_hits:
                    # already answered from cache above: terminal before
                    # the misses even enqueue
                    req.videos[p] = 'cached'
                    req.pending -= 1
                self._requests[req.id] = req
                self._inflight_videos += len(miss_paths)
                if _live_session is not None:
                    tasks: List[_ServeTask] = [_LiveServeTask(
                        miss_paths[0], req,
                        out_root=args['output_path'],
                        session=_live_session)]
                    _live_session.bind(req)
                else:
                    tasks = [_ServeTask(p, req,
                                        out_root=args['output_path'],
                                        segment=segment)
                             for p in miss_paths]
                # enqueue under the admission lock: eviction (pool.put)
                # also runs under it, so a worker can't be judged idle
                # and closed between admission and enqueue
                worker.submit(tasks)
            self.stats.bump('submitted')
            self._record_admission(t0_admit, req, videos=len(miss_paths))
            return protocol.ok(request_id=req.id,
                               trace_id=trace_ctx.trace_id)
        self.stats.bump('rejected')
        return protocol.error('worker churn outpaced admission; retry',
                              code=protocol.ERR_SHED)

    def _submit_fused(self, features, video_paths,
                      overrides: Optional[Dict[str, Any]] = None,
                      timeout_s: Optional[float] = None,
                      range_s=None,
                      priority: str = 'interactive',
                      traceparent: Optional[str] = None) -> Dict[str, Any]:
        """One ``features=[...]`` submit: validate and pre-flight EVERY
        family's config first (a fused request admits whole or not at
        all on config grounds — family 3 failing validation after
        families 1–2 queued would strand work and quota), then fan out
        one child submit per family under one shared trace context.
        Families answered entirely from cache terminate at birth inside
        their child submit, exactly as today; the warm decode farm's
        content-hash memoization (``cache/key.py``) makes the N
        children's hash passes one streaming read per video."""
        from video_features_tpu.config import (
            resolve_fused_features, split_fused_overrides,
        )
        try:
            fams = resolve_fused_features(features)
        except (TypeError, ValueError) as e:
            self.stats.bump('rejected')
            return protocol.error(f'invalid features: {e}',
                                  code=protocol.ERR_INVALID)
        bad = [f for f in fams if f not in PACKED_FEATURES]
        if bad:
            self.stats.bump('rejected')
            return protocol.error(
                f'features {bad} have no packed/serving support; '
                f'serveable: {", ".join(sorted(PACKED_FEATURES))}',
                code=protocol.ERR_UNSUPPORTED)
        if not isinstance(video_paths, (list, tuple)) or not video_paths:
            self.stats.bump('rejected')
            return protocol.error('video_paths must be a non-empty list',
                                  code=protocol.ERR_INVALID)
        paths = [str(p) for p in video_paths]
        trace_ctx = accept_traceparent(traceparent)
        # family-scoped overrides ('<family>.<knob>') peel off to their
        # family; everything else is shared — same split as the fused CLI
        shared, scoped = split_fused_overrides(overrides or {}, fams)
        fam_overrides: Dict[str, Dict[str, Any]] = {}
        for fam in fams:
            o = dict(shared)
            o.update(scoped.get(fam, {}))
            fam_overrides[fam] = o
            try:
                self._resolve_entry_config(fam, paths, o)
            except Exception as e:
                self.stats.bump('rejected')
                return protocol.error(f'invalid request for {fam!r}: {e}',
                                      code=protocol.ERR_INVALID)

        with self._lock:
            if self._draining:
                self.stats.bump('rejected')
                return protocol.error('draining', code=protocol.ERR_SHED)
            self._next_id += 1
            parent = FusedRequest(f'r{self._next_id:06d}', fams, paths,
                                  priority=priority, trace=trace_ctx)
            self._requests[parent.id] = parent

        children: Dict[str, Request] = {}
        errors: Dict[str, str] = {}
        for fam in fams:
            resp = self.submit(fam, paths,
                               overrides=fam_overrides[fam],
                               timeout_s=timeout_s, range_s=range_s,
                               priority=priority,
                               traceparent=trace_ctx.traceparent())
            if resp.get('ok'):
                with self._lock:
                    children[fam] = self._requests[resp['request_id']]
            else:
                # admission rejection mid-fan-out (queue_full under a
                # race; config errors were pre-flighted): the family
                # records as a terminal failed child so the umbrella
                # still completes from the admitted siblings
                errors[fam] = str(resp.get('error'))
                child = Request(f'{parent.id}.{fam}', fam, paths, None,
                                priority=priority, trace=trace_ctx)
                for p in paths:
                    child.videos[p] = 'failed'
                child.pending = 0
                child.done_t = time.monotonic()
                children[fam] = child
        if not any(fam not in errors for fam in fams):
            # nothing admitted: the umbrella is dead on arrival
            with self._lock:
                self._requests.pop(parent.id, None)
            return protocol.error(
                'fused submit admitted no family: '
                + '; '.join(f'{f}: {e}' for f, e in errors.items()),
                code=protocol.ERR_INTERNAL)

        with self._lock:
            parent.children = children
            for child in children.values():
                child.fused_parent = parent
            # terminal-at-birth children (all-cache-hit families, or
            # every family rejected-but-one-cached) completed BEFORE the
            # parent hook attached — close the umbrella here if so
            done = (parent.done_t is None
                    and all(c.done_t is not None
                            for c in children.values()))
            if done:
                self._record_done_locked(parent)
        if done:
            self._fire_completion_listeners(parent)
        out: Dict[str, Any] = {'request_id': parent.id,
                               'trace_id': trace_ctx.trace_id,
                               'requests': {f: c.id
                                            for f, c in children.items()}}
        if errors:
            out['errors'] = errors
        return protocol.ok(**out)

    def submit_live(self, feature_type: str, session,
                    overrides: Optional[Dict[str, Any]] = None,
                    timeout_s: Optional[float] = None,
                    priority: str = 'interactive',
                    traceparent: Optional[str] = None) -> Dict[str, Any]:
        """Admit one LIVE session: a long-lived request whose frames
        arrive over time (``session`` is an ``ingress.live.LiveSession``
        — or anything with ``pseudo_path``/``bind``/``windows``/
        ``send_window``). Takes the same admission path as
        :meth:`submit` (deadline, priority shed, queue depth: a session
        occupies ONE admission slot until it ends), but its task decodes
        nothing and saves nothing — windows stream in from the session
        and features stream back out through it, per window."""
        return self.submit(feature_type, [session.pseudo_path],
                           overrides=overrides, timeout_s=timeout_s,
                           priority=priority, traceparent=traceparent,
                           _live_session=session)

    def _resolve_entry_config(self, feature_type: str, paths: List[str],
                              overrides: Optional[Dict[str, Any]] = None,
                              ) -> tuple:
        """Resolve one entry's full config + pool key — THE one merge
        sequence (base overrides → per-call overrides → worklist +
        profile pinning → ``load_config`` → per-run knob rejection),
        shared by the submit path and the boot-time pre-warm so the two
        can never derive DIFFERENT pool keys for the same entry (a
        drifted pre-warm key would make the first real request silently
        rebuild while the pre-warmed entry sat unused until evicted).
        Raises on an invalid config; callers translate (submit → a
        protocol error, prewarm → a structured boot event)."""
        merged = dict(self.base_overrides)
        merged.update(overrides or {})
        merged['video_paths'] = paths
        merged.pop('file_with_video_paths', None)
        merged['feature_type'] = feature_type
        merged['profile'] = True              # tracer feeds /metrics
        args = load_config(feature_type, overrides=merged)
        if args.get('manifest_out'):
            # the run manifest is a PER-RUN artifact (outcomes of one
            # bounded worklist); a resident worker has no run end, its
            # video table would grow unboundedly, and concurrent workers
            # would clobber one shared path — the serve surfaces for the
            # same data are the metrics document and the merged trace
            event(logging.WARNING,
                  'manifest_out is a per-run CLI knob; ignored by the '
                  'serve daemon (use metrics / metrics_prom / trace_out)',
                  subsystem='serve', path=str(args['manifest_out']))
            args['manifest_out'] = None
        return args, pool_key(resolve_mesh_devices(args))

    def _spawn_worker(self, args: Config, key: tuple) -> _Worker:
        """Build one warm-pool entry end to end: transplant, pin chip
        residency, eagerly resolve its programs against the persistent
        executable store (AFTER placement — executables bind to the
        assigned chips), wire liveness, start the worker, and insert it.
        The cold-start cost serving exists to amortize lives here —
        shared verbatim by a cold submit and the boot-time pre-warm, so
        a pre-warmed entry IS the entry a later request would have
        built. Raises on build failure (callers translate: submit → a
        protocol error, prewarm → a structured boot event). Callers
        hold the per-key build lock."""
        label = args['feature_type'] + (
            f"/{args['model_name']}" if args.get('model_name') else '')
        extractor = create_extractor(args)
        worker = _Worker(self, key, label, extractor,
                         self.idle_flush_s, self.max_batch_wait_s)
        # pin residency BEFORE the first batch flows: least-loaded
        # chip(s) via the placer (a mesh entry takes mesh_devices chips)
        worker.devices = self._place_extractor(extractor)
        # zero cold start (aot/): load-or-compile every declared program
        # at the placed residency; {'loaded': n, 'compiled': n} decides
        # which builds_* counter this entry lands on. No-op (all zeros)
        # without aot_enabled in the entry's config.
        warm = extractor.aot_warm()
        # liveness ledger rides the tracer's progress hook — wired
        # before the first stage records
        self._wire_watchdog(worker)
        worker.start()
        rec = getattr(extractor.tracer, 'recorder', None)
        with self._lock:
            if warm['loaded'] > 0 and warm['compiled'] == 0:
                self._builds_loaded += 1
            else:
                self._builds_compiled += 1
            if rec is not None:
                self._trace_recorders.append(rec)
            self._retired.extend(self.pool.put(key, worker))
        return worker

    def prewarm(self, specs) -> Dict[str, Any]:
        """Build warm-pool entries at BOOT, before any request arrives
        (the ``serve_prewarm`` knob): each ``'family[@lane]'`` spec is
        resolved against the base overrides exactly like a cold submit
        and spawned through the same :meth:`_spawn_worker` path — so
        with ``aot_enabled`` and an unchanged program set, the whole
        boot is compile-free (``builds_loaded`` entries, zero
        ``builds_compiled``) and the first request packs into an
        already-resident executable. A spec that fails to build is a
        structured boot event, never a crashed daemon — the family
        simply cold-builds on its first request as before."""
        report: Dict[str, Any] = {'entries': 0, 'programs_loaded': 0,
                                  'programs_compiled': 0, 'errors': []}
        specs = list(specs or ())
        if len(specs) > self.pool.capacity:
            # every put over capacity LRU-retires an earlier entry, so
            # the boot would pay full builds for entries the first
            # request can't find — name the misconfiguration instead of
            # silently wasting the warm-up
            event(logging.WARNING,
                  'serve_prewarm names more entries than the warm pool '
                  'holds; the earliest pre-warmed entries will be '
                  'evicted before the first request arrives',
                  subsystem='serve', specs=len(specs),
                  pool_size=self.pool.capacity)
        for spec in specs:
            family, _, lane = str(spec).partition('@')
            if family == 'index':
                # the index query program is not a warm-pool entry — it
                # pre-warms through the index service's own executable
                # store path (loaded from PROGRAMS.lock-pinned AOT state
                # when unchanged, compiled otherwise)
                if self.index_service is None:
                    report['errors'].append(
                        f'{spec}: index_enabled is false')
                    continue
                outcome = self.index_service.prewarm()
                report['entries'] += 1
                report['programs_loaded'] += int(outcome == 'loaded')
                report['programs_compiled'] += int(outcome == 'compiled')
                continue
            try:
                # a virtual '.live'-style pseudo path: config validation
                # needs a non-empty worklist, and nothing should warn
                # about (or expect) a real file at boot
                args, key = self._resolve_entry_config(
                    family, ['__prewarm__.live'],
                    {'compute_dtype': lane} if lane else None)
                with self._lock:
                    build_lock = self._build_locks.setdefault(
                        key, threading.Lock())
                with build_lock:
                    existing = self.pool.peek(key)
                    if existing is not None and not (existing.closed
                                                     or existing.crashed):
                        continue              # duplicate spec: one entry
                    worker = self._spawn_worker(args, key)
                report['entries'] += 1
                report['programs_loaded'] += worker.ex.aot_stats['loaded']
                report['programs_compiled'] += \
                    worker.ex.aot_stats['compiled']
            except Exception as e:
                event(logging.WARNING,
                      'serve pre-warm spec failed to build; the family '
                      'will cold-build on its first request',
                      subsystem='serve', exc_info=True, spec=str(spec))
                report['errors'].append(f'{spec}: {e}')
        if report['entries'] or report['errors']:
            event(logging.INFO, 'serve pre-warm complete',
                  subsystem='serve', **{k: v for k, v in report.items()
                                        if k != 'errors'},
                  failed=len(report['errors']))
        return report

    def attach_ingress(self, ingress) -> None:
        """Register the network front door (``ingress/``) so drain can
        quiesce it: stop accepting, end live sessions, reap half-open
        connections."""
        self.ingress = ingress

    def _place_extractor(self, extractor) -> Optional[List]:
        """Assign a fresh entry's extractor its resident chip(s): the
        least-loaded local device(s) of its platform — ``mesh_devices``
        of them for a mesh-sharded entry. Best-effort: placement must
        never fail a build (a placement error just leaves the extractor
        on its default device 0 residency)."""
        try:
            from video_features_tpu.utils.device import jax_devices_all
            local = jax_devices_all(extractor.device)
            n = int(getattr(extractor, 'mesh_devices', 1) or 1)
            # REAL bytes, not '1 entry': a bf16 fast-lane entry is ~half
            # the params HBM of its fp32 sibling, and the placer ranks
            # chips by resident bytes so the accounting sees that
            nbytes = extractor.params_nbytes()
            devices = self._placer.assign(local, n, nbytes=nbytes)
            try:
                extractor.place_on(devices)
            except Exception:
                # assign() already counted these chips — give them back,
                # or the failed placement skews every future least-loaded
                # decision for the server's lifetime
                self._placer.release(devices, nbytes=nbytes)
                raise
            # remember the EXACT charged bytes for the symmetric release
            # (recomputing at retirement could drift if the extractor's
            # buffers changed — the ledger must always net to zero)
            extractor._placement_nbytes = nbytes
            return devices
        except Exception:
            import logging

            from video_features_tpu.obs.events import event
            event(logging.WARNING, 'device placement failed; entry stays '
                  'on the default device', subsystem='serve',
                  exc_info=True)
            return None

    def _release_placement(self, worker: '_Worker') -> None:
        """Return a retired entry's chips — and its resident bytes — to
        the placer (idempotent — retirement paths can race: crash vs
        reap)."""
        devices, worker.devices = worker.devices, None
        if devices:
            self._placer.release(
                devices,
                nbytes=getattr(worker.ex, '_placement_nbytes', 0))

    def _answer_cache_hits(self, args: Config, paths: List[str],
                           segment=None) -> List[str]:
        """Materialize every video the feature cache already holds for
        this request's recipe into its output root; returns the hit
        paths. Never raises — any cache-side failure is a miss, and the
        normal extraction path owns reporting it. ``segment`` keys (and
        names) a range extraction separately from the full video."""
        from video_features_tpu.cache import (
            FeatureCache, log_cache_error, run_fingerprint, video_cache_key,
        )
        from video_features_tpu.parallel.packing import segment_name
        hits: List[str] = []
        try:
            l2 = args.get('cache_l2_dir')
            if l2:
                # fleet tier: an admission-time hit may be served from
                # the shared L2 a PEER host published — the request goes
                # terminal without ever decoding here (docs/fleet.md)
                from video_features_tpu.fleet.tier import TieredFeatureCache
                cache = TieredFeatureCache.get_pair(
                    args.get('cache_dir'), l2, args.get('cache_max_bytes'))
            else:
                cache = FeatureCache.get(args.get('cache_dir'),
                                         args.get('cache_max_bytes'))
            with self._lock:
                self._caches[cache.cache_dir] = cache
            fp = run_fingerprint(args)
        except Exception:
            log_cache_error('serve-side open')
            return hits
        for p in paths:
            try:
                if cache.fetch_to(video_cache_key(p, fp, segment=segment),
                                  args['output_path'],
                                  segment_name(p, segment),
                                  fingerprint=fp):
                    hits.append(p)
            except Exception:
                # e.g. the video file itself is unreadable (can't be
                # content-hashed): let extraction fail it properly
                log_cache_error(f'serve-side lookup for {p}')
        return hits

    def status(self, request_id: str) -> Dict[str, Any]:
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                return protocol.error(f'unknown request_id {request_id!r}',
                                      code=protocol.ERR_NOT_FOUND)
            return protocol.ok(**req.snapshot())

    def _fold_retired_locked(self, report: Dict[str, Dict]) -> None:
        from video_features_tpu.utils.tracing import merge_reports
        self._retired_stages = merge_reports([self._retired_stages, report])

    def _reap_retired_locked(self) -> None:
        """Free evicted workers whose graceful drain has finished: fold
        the tracer report into the merged history and drop the worker so
        its extractor — transplanted device params plus compiled
        executables — stops pinning memory. Caller holds ``self._lock``."""
        for w in list(self._retired):
            if not w.thread.is_alive():
                self._fold_retired_locked(w.ex.tracer.report())
                self._retired.remove(w)
                self._release_placement(w)
                self._wd_forget(w)

    def metrics(self) -> Dict[str, Any]:
        with self._lock:
            self._reap_retired_locked()
            depth = self._inflight_videos
            draining = self._draining
            builds_compiled = self._builds_compiled
            builds_loaded = self._builds_loaded
            reports = {}
            placements = {}
            for i, w in enumerate(self.pool.entries() + self._retired):
                label = w.label if w.label not in reports \
                    else f'{w.label}#{i}'
                reports[label] = w.ex.tracer.report()
                if w.devices:
                    # which chip(s) this entry is resident on — the
                    # routing table a multi-family server actually uses
                    placements[label] = [f'd{d.id}' for d in w.devices]
            if self._retired_stages:
                reports['retired'] = dict(self._retired_stages)
            caches = list(self._caches.values())
            # live async-loop depth: dispatched-but-unmaterialized device
            # batches across every warm worker (run_packed maintains the
            # per-extractor attribute; a monitoring read needs no lock)
            inflight_batches = sum(
                int(getattr(w.ex, '_inflight_now', 0) or 0)
                for w in self.pool.entries() + self._retired)
            # decode-farm view: each farm-backed warm worker keeps a
            # live DecodeFarm handle on its extractor; the merged stats
            # (busy workers, ring bytes, respawns, dedupes) are the
            # 'farm' section / vft_farm_* families
            farms = [w.ex._farm.stats()
                     for w in self.pool.entries() + self._retired
                     if getattr(w.ex, '_farm', None) is not None]
            # executable-store view (aot/): the stores the live workers
            # were built against (deduped by dir — entries usually share
            # one), plus the per-worker program path counters
            aot_stores: Dict[str, Any] = {}
            aot_loaded = aot_compiled = 0
            for w in self.pool.entries() + self._retired:
                store = getattr(w.ex, '_aot_store', None)
                if store is not None:
                    aot_stores[store.aot_dir] = store
                st = getattr(w.ex, 'aot_stats', None) or {}
                aot_loaded += int(st.get('loaded', 0))
                aot_compiled += int(st.get('compiled', 0))
        pool_stats = self.pool.stats()
        # builds_* ≤ misses: concurrent cold submits for one key all
        # count misses but transplant exactly once (the per-key build
        # lock). The split is the zero-cold-start audit surface: an
        # entry whose programs all LOADED from the executable store is
        # builds_loaded; anything that compiled is builds_compiled.
        pool_stats['builds_compiled'] = builds_compiled
        pool_stats['builds_loaded'] = builds_loaded
        # placement view: entry label → resident chips, plus per-device
        # resident-entry counts (the vft_device_resident_entries gauges)
        pool_stats['placements'] = placements
        pool_stats['device_residents'] = self._placer.snapshot()
        # REAL per-chip residency bytes (bf16 entries count ~half their
        # fp32 siblings) — the vft_device_resident_bytes gauges
        pool_stats['device_resident_bytes'] = self._placer.snapshot_bytes()
        from video_features_tpu.cache.store import merge_cache_stats
        from video_features_tpu.farm.farm import merge_farm_stats
        ingress_stats = None
        if self.ingress is not None:
            try:
                ingress_stats = self.ingress.stats()
            except Exception:
                event(logging.WARNING, 'ingress stats unavailable; '
                      'metrics document degrades to enabled=False',
                      subsystem='serve', exc_info=True)
                ingress_stats = None
        # vft-flight telemetry: span-ring loss across the live
        # recorders, the watchdog's progress-ledger view
        recorders = self._all_recorders()
        trace_stats = {'recorders': len(recorders),
                       'events_dropped': sum(r.dropped
                                             for r in recorders)}
        watchdog_stats = (self.watchdog.snapshot()
                          if self.watchdog is not None else None)
        from video_features_tpu.aot.store import merge_exec_stats
        aot_stats = merge_exec_stats(s.stats()
                                     for s in aot_stores.values())
        aot_stats['programs_loaded'] = aot_loaded
        aot_stats['programs_compiled'] = aot_compiled
        return metrics_mod.build_metrics(
            self._started_at, depth, self.queue_depth, draining,
            pool_stats, self.stats, reports,
            cache_stats=merge_cache_stats(c.stats() for c in caches),
            inflight_batches=inflight_batches,
            farm_stats=merge_farm_stats(farms),
            ingress_stats=ingress_stats,
            trace_stats=trace_stats,
            watchdog_stats=watchdog_stats,
            aot_stats=aot_stats,
            index_stats=(self.index_service.stats()
                         if self.index_service is not None else None),
            slo_stats=(self.slo.stats()
                       if self.slo is not None else None))

    # -- completion callbacks (worker threads) -------------------------------

    def _record_done_locked(self, req: Request) -> None:
        """Terminal-request bookkeeping (caller holds ``self._lock``):
        stamp completion time and age out the oldest terminal requests —
        status() history is bounded, a resident daemon's request table
        must not grow with lifetime traffic."""
        req.done_t = time.monotonic()
        self._done_ids.append(req.id)
        while len(self._done_ids) > REQUEST_HISTORY:
            self._requests.pop(self._done_ids.popleft(), None)

    def _fire_completion_listeners(self, req: Request) -> None:
        for listener in list(self.completion_listeners):
            # e.g. the ingress gateway releasing this request's tenant
            # concurrency slot; a listener bug must not lose completions
            try:
                listener(req)
            except Exception:
                # a broken listener must not take down completion, but a
                # silent one leaks what it guards (per-tenant quota units)
                event(logging.WARNING, 'completion listener failed',
                      subsystem='serve', exc_info=True,
                      request_id=req.id)

    def _fused_child_done(self, parent: 'FusedRequest') -> None:
        """A fused child reached terminal state: close the umbrella when
        it was the last one. No completed/failed/latency accounting —
        the children already counted; the umbrella's one side effect is
        the completion listeners (quota release)."""
        with self._lock:
            done = (parent.done_t is None and parent.children
                    and all(c.done_t is not None
                            for c in parent.children.values()))
            if done:
                self._record_done_locked(parent)
        if done:
            self._fire_completion_listeners(parent)

    def _after_completion(self, req: Request) -> None:
        """Lock-free completion accounting, shared by the worker path
        and the all-cache-hit terminal-at-birth path."""
        self.stats.bump('completed')
        if req.state() in ('partial', 'failed'):
            self.stats.bump('failed')
        self.stats.observe_latency(req.done_t - req.t0)
        self._fire_completion_listeners(req)
        parent = getattr(req, 'fused_parent', None)
        if parent is not None:
            self._fused_child_done(parent)
        if self.metrics_path:
            # building the metrics document takes the server lock and
            # snapshots every tracer — skip it entirely when no
            # mirror is configured
            doc = self.metrics()
            metrics_mod.write_metrics_file(self.metrics_path, doc,
                                           prom_text=self._prometheus(doc))

    def _finish_video(self, task, state: str) -> None:
        req = task.request
        with self._lock:
            if req.videos.get(task.path) == 'pending':
                req.videos[task.path] = state
                req.pending -= 1
                self._inflight_videos -= 1
            completed = req.pending == 0 and req.done_t is None
            if completed:
                self._record_done_locked(req)
        if completed:
            self._after_completion(req)

    def _video_done(self, task) -> None:
        # 'cached': an in-worker cache hit — the video missed at admission
        # but another request published it before this one reached decode
        if getattr(task, 'cached', False):
            self.stats.bump('cached_videos')
            self._finish_video(task, 'cached')
            return
        state = ('skipped' if task.skipped
                 else 'failed' if task.failed else 'saved')
        self._finish_video(task, state)

    def _video_expired(self, task) -> None:
        self.stats.bump('expired_videos')
        self._finish_video(task, 'expired')

    def _retire_crashed(self, worker: _Worker) -> None:
        with self._lock:
            # identity-checked: a healthy replacement may already serve
            # this key — removing by key alone would evict IT instead
            self.pool.remove(worker.key, worker)
            self._fold_retired_locked(worker.ex.tracer.report())
            self._release_placement(worker)
            self._wd_forget(worker)

    # -- endpoint ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return                        # socket closed: drained
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        with conn:
            rfile = conn.makefile('rb')
            wfile = conn.makefile('wb')
            for line in rfile:
                if not line.strip():
                    continue
                try:
                    msg = protocol.decode(line)
                    resp = self._dispatch(msg)
                except Exception as e:
                    resp = protocol.error(f'{type(e).__name__}: {e}',
                                          code=protocol.ERR_INTERNAL)
                try:
                    wfile.write(protocol.encode(resp))
                    wfile.flush()
                except (OSError, ValueError):
                    return                    # client went away

    def _dispatch(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        # version gate first: an incompatible client gets a structured
        # rejection naming both versions (and echoing its request_id),
        # never a field-validation error about a schema it doesn't speak
        bad_version = protocol.check_version(msg)
        if bad_version is not None:
            return bad_version
        cmd = msg.get('cmd')
        if cmd == protocol.CMD_PING:
            return protocol.ok(draining=self._draining, v=protocol.VERSION)
        if cmd == protocol.CMD_SUBMIT:
            unknown = set(msg) - set(protocol.SUBMIT_FIELDS)
            if unknown:
                return protocol.error(
                    f'unknown submit fields: {sorted(unknown)}',
                    code=protocol.ERR_INVALID)
            return self.submit(msg.get('feature_type'),
                               msg.get('video_paths'),
                               overrides=msg.get('overrides'),
                               timeout_s=msg.get('timeout_s'),
                               range_s=msg.get('range'),
                               priority=msg.get('priority', 'interactive'),
                               traceparent=msg.get('traceparent'),
                               features=msg.get('features'))
        if cmd == protocol.CMD_STATUS:
            return self.status(msg.get('request_id'))
        if cmd == protocol.CMD_TRACE:
            return self.request_trace(msg.get('request_id'))
        if cmd == protocol.CMD_METRICS:
            return protocol.ok(metrics=self.metrics())
        if cmd == protocol.CMD_METRICS_PROM:
            # Prometheus text exposition 0.0.4 of the same state
            return protocol.ok(text=self._prometheus(self.metrics()))
        if cmd == protocol.CMD_SEARCH:
            if self.index_service is None:
                return protocol.error(
                    'index is not enabled on this server '
                    '(start with index_enabled=true)',
                    code=protocol.ERR_UNSUPPORTED)
            try:
                if msg.get('video_path') is not None:
                    return protocol.ok(**self.index_service.search_by_video(
                        msg['video_path'],
                        features=msg.get('features'),
                        k=msg.get('k', 10),
                        timeout_s=msg.get('timeout_s')))
                return protocol.ok(**self.index_service.search_vector(
                    msg.get('family'), msg.get('vector'),
                    k=msg.get('k', 10)))
            except (TypeError, ValueError, KeyError) as e:
                # malformed query (missing vector, unknown family, bad
                # dim): the CLIENT's error, answered structurally — a
                # bad search must never take down the handler thread
                return protocol.error(f'search failed: {e}',
                                      code=protocol.ERR_INVALID)
        if cmd == protocol.CMD_INDEX_STATUS:
            if self.index_service is None:
                return protocol.ok(index={'enabled': False})
            return protocol.ok(index=self.index_service.stats())
        if cmd == protocol.CMD_DRAIN:
            self.drain(wait=False)
            return protocol.ok(draining=True)
        return protocol.error(
            f'unknown cmd {cmd!r}; known: {", ".join(protocol.COMMANDS)}',
            code=protocol.ERR_INVALID)


def serve_main(argv: List[str]) -> int:
    """``python -m video_features_tpu serve`` entry point."""
    from video_features_tpu.config import parse_dotlist
    serve_cfg, base = split_serve_config(parse_dotlist(argv))
    server = ExtractionServer(
        base_overrides=base,
        host=serve_cfg['serve_host'],
        port=serve_cfg['serve_port'],
        queue_depth=serve_cfg['serve_queue_depth'],
        pool_size=serve_cfg['serve_warm_pool_size'],
        idle_flush_s=serve_cfg['serve_idle_flush_s'],
        max_batch_wait_s=serve_cfg['serve_max_batch_wait_s'],
        default_timeout_s=serve_cfg['serve_default_timeout_s'],
        metrics_path=serve_cfg['serve_metrics_path'],
        batch_shed_fraction=serve_cfg['serve_batch_shed_fraction'],
    ).start()
    server.install_signal_handlers()
    # zero cold start: build the configured warm-pool entries BEFORE the
    # endpoint line prints (scrapers treat that line as readiness) — on
    # an unchanged program set with aot_enabled this loads executables
    # instead of compiling, and the first request is compile-free
    if serve_cfg.get('serve_prewarm'):
        server.prewarm(serve_cfg['serve_prewarm'])
    if server.blackbox is not None:
        # fatal-signal dumps (SIGQUIT/SIGABRT) compose with the graceful
        # SIGTERM/SIGINT drain above — different signals, both covered
        from video_features_tpu.obs.blackbox import install_signal_dump
        install_signal_dump(server.blackbox)
    # machine-greppable endpoint line (tests and tooling scrape it)
    # vft-lint: ok=stdout-purity — the daemon's documented startup line
    # (docs/serving.md): clients scrape host:port from it; serve-mode
    # stdout is not a feature stream (features go to request out_roots)
    print(f'serving on {server.host}:{server.port} '
          f'(pid {os.getpid()}; queue_depth='
          f'{serve_cfg["serve_queue_depth"]}, warm_pool='
          f'{serve_cfg["serve_warm_pool_size"]})', flush=True)
    if serve_cfg['serve_ingress_port'] is not None:
        # the network front door (ingress/): HTTP/1.1 + chunked, API-key
        # tenancy, quotas/priorities, segment queries, live sessions
        from video_features_tpu.ingress.gateway import IngressGateway
        gateway = IngressGateway(
            server,
            host=serve_cfg['serve_ingress_host'],
            port=serve_cfg['serve_ingress_port'],
            auth_file=serve_cfg['serve_ingress_auth_file'],
            max_body_bytes=(serve_cfg['serve_ingress_max_body_mb']
                            * (1 << 20)),
            max_connections=serve_cfg['serve_ingress_max_connections'],
        ).start()
        # second machine-greppable endpoint line (same scraping contract)
        # vft-lint: ok=stdout-purity — documented startup line (ingress)
        print(f'ingress on {gateway.host}:{gateway.port} '
              f'(tenants={gateway.n_tenants})', flush=True)
    server.serve_forever()
    # vft-lint: ok=stdout-purity — shutdown line of the same contract
    print('serve: drained, exiting', flush=True)
    sys.stdout.flush()
    sys.stderr.flush()
    # hard exit: the workers ran XLA on non-main threads, and letting the
    # interpreter walk C++ static destructors after that intermittently
    # aborts ("terminate called without an active exception") — every
    # output is already durably published (atomic writes) and both
    # streams are flushed, so skip teardown and give supervisors a
    # clean 0
    os._exit(0)
