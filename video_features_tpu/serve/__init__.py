"""Warm-pool extraction service: the long-running serving layer.

``python -m video_features_tpu serve`` starts the daemon
(:mod:`serve.server`); :mod:`serve.client` talks to it;
:mod:`serve.pool` keeps transplanted weights + compiled executables
resident; :mod:`serve.metrics` is the live health surface. See
``docs/serving.md``.
"""
from video_features_tpu.serve.client import ServeClient, ServeError  # noqa: F401
from video_features_tpu.serve.pool import WarmPool  # noqa: F401

__all__ = ['ServeClient', 'ServeError', 'WarmPool', 'ExtractionServer']


def __getattr__(name):
    # ExtractionServer pulls in config/registry (and transitively jax at
    # request time); keep the package importable feather-light for clients
    if name == 'ExtractionServer':
        from video_features_tpu.serve.server import ExtractionServer
        return ExtractionServer
    raise AttributeError(name)
