"""Thin client for the warm-pool extraction service.

One connection per call (submit/status/metrics are sub-millisecond
against a loopback endpoint — holding a pooled connection buys nothing
and would add reconnect logic); ``wait`` polls status. Raises
:class:`ServeError` for any ``ok: false`` response so callers get Python
exceptions, not dicts to inspect.
"""
from __future__ import annotations

import random
import socket
import time
from typing import Any, Dict, List, Optional

from video_features_tpu.serve import protocol


class ServeError(RuntimeError):
    """The server answered ``ok: false`` (the message is the reason).

    ``code`` (wire 1.4) is the STRUCTURED failure class — one of the
    ``protocol.ERR_*`` constants, or None from a pre-1.4 server. The
    fleet router's failover switch keys on it exclusively: ``shed``,
    ``connect_refused``, and ``deadline`` are retry-next-host;
    everything else propagates. ``extra`` carries the response's other
    fields (``depth``/``capacity`` on queue_full, …) verbatim."""

    def __init__(self, message: str, code: Optional[str] = None,
                 extra: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(message)
        self.code = code
        self.extra = dict(extra) if extra else {}

    @property
    def retryable(self) -> bool:
        """True when a DIFFERENT backend could plausibly accept this
        request (this host shed it, refused the connect, or sat on it
        past the deadline) — the one bit the router's failover needs."""
        return self.code in (protocol.ERR_SHED,
                             protocol.ERR_CONNECT_REFUSED,
                             protocol.ERR_DEADLINE)


class ServeConnectError(ServeError, ConnectionRefusedError):
    """No listener answered within ``connect_timeout_s`` (code
    ``connect_refused``). Also a :class:`ConnectionRefusedError` so
    pre-1.4 callers catching the OS exception keep working."""

    def __init__(self, message: str) -> None:
        ServeError.__init__(self, message,
                            code=protocol.ERR_CONNECT_REFUSED)


class ServeDeadlineError(ServeError, TimeoutError):
    """The request outlived the caller's wait deadline (code
    ``deadline``). Also a :class:`TimeoutError` for pre-1.4 callers."""

    def __init__(self, message: str) -> None:
        ServeError.__init__(self, message, code=protocol.ERR_DEADLINE)


class ServeClient:
    """``connect_timeout_s`` is a DEADLINE, not a single attempt: a
    refused connect (daemon still warming up, supervisor restart window)
    retries with bounded exponential backoff + jitter until the deadline
    passes — so ``start daemon & client.submit(...)`` just works without
    the caller hand-rolling a poll loop. Unreachable-host errors
    (timeouts, routing) are NOT retried; only connection-refused is,
    because that is the one error a late-binding listener cures.

    Every message carries the protocol version (``v``). Compatibility is
    deliberately one-way: an OLD client against a NEW server keeps
    working (missing ``v`` = v1), while a NEW client against a
    pre-versioning server fails LOUDLY on submit (its strict field check
    rejects ``v`` with a structured error naming the field) — the
    version field must flow for major-version negotiation to exist at
    all, and a clear rejection beats silently dropping the handshake."""

    # backoff: 50ms doubling to 1s, each delay jittered ±50% so a
    # thundering herd of clients doesn't re-refuse in lockstep
    _BACKOFF_BASE_S = 0.05
    _BACKOFF_CAP_S = 1.0

    def __init__(self, port: int, host: str = '127.0.0.1',
                 connect_timeout_s: float = 10.0) -> None:
        self.host, self.port = host, int(port)
        self.connect_timeout_s = connect_timeout_s

    def _connect(self) -> socket.socket:
        deadline = time.monotonic() + self.connect_timeout_s
        delay = self._BACKOFF_BASE_S
        while True:
            remaining = deadline - time.monotonic()
            try:
                conn = socket.create_connection(
                    (self.host, self.port), timeout=max(remaining, 0.001))
                conn.settimeout(None)         # extraction can take a while
                return conn
            except ConnectionRefusedError:
                if time.monotonic() + delay >= deadline:
                    raise ServeConnectError(
                        f'connect to {self.host}:{self.port} refused for '
                        f'{self.connect_timeout_s}s') from None
                # clamp the jittered sleep to the remaining budget so
                # the deadline is honored even at the jitter's top end
                time.sleep(max(0.0, min(delay * random.uniform(0.5, 1.5),
                                        deadline - time.monotonic())))
                delay = min(delay * 2, self._BACKOFF_CAP_S)

    @staticmethod
    def _read_response(rfile) -> Dict[str, Any]:
        line = rfile.readline()
        if not line:
            # a mid-request connection loss looks exactly like a shed to
            # the caller's retry logic: another host may well accept it
            raise ServeError('server closed the connection',
                             code=protocol.ERR_SHED)
        resp = protocol.decode(line)
        if not resp.get('ok'):
            raise ServeError(resp.get('error', 'unknown server error'),
                             code=resp.get('code'),
                             extra={k: v for k, v in resp.items()
                                    if k not in ('ok', 'error', 'code')})
        return resp

    def _call(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        msg.setdefault('v', protocol.VERSION)
        with self._connect() as conn:
            conn.sendall(protocol.encode(msg))
            with conn.makefile('rb') as rfile:
                return self._read_response(rfile)

    # -- commands ------------------------------------------------------------

    def ping(self) -> bool:
        return bool(self._call({'cmd': protocol.CMD_PING}).get('ok'))

    def submit(self, feature_type: Optional[str], video_paths: List[str],
               overrides: Optional[Dict[str, Any]] = None,
               timeout_s: Optional[float] = None,
               range_s: Optional[List[float]] = None,
               priority: Optional[str] = None,
               traceparent: Optional[str] = None,
               features: Optional[List[str]] = None) -> str:
        """Enqueue one extraction request; returns its request_id.
        Raises :class:`ServeError` on rejection (queue_full, draining,
        invalid config, …) — backpressure is the caller's to handle.
        ``range_s=[start_s, end_s]`` makes it a segment query (only the
        covered windows decode; outputs named ``_seg<a>-<b>ms``);
        ``priority`` ('interactive' | 'batch') feeds admission — a
        saturated queue sheds batch before interactive; ``traceparent``
        (W3C ``00-<trace>-<span>-<flags>``) joins the request to a
        caller-owned distributed trace (minted server-side otherwise);
        ``features=['i3d', 'clip', ...]`` (v1.2) submits a FUSED
        multi-family request — one umbrella request_id (returned) with
        per-family children, ``feature_type`` ignored; family-scoped
        override keys spell ``<family>.<knob>``."""
        msg: Dict[str, Any] = {'cmd': protocol.CMD_SUBMIT,
                               'feature_type': feature_type,
                               'video_paths': list(video_paths)}
        if features is not None:
            msg['features'] = list(features)
        if overrides:
            msg['overrides'] = dict(overrides)
        if timeout_s is not None:
            msg['timeout_s'] = float(timeout_s)
        if range_s is not None:
            msg['range'] = [float(range_s[0]), float(range_s[1])]
        if priority is not None:
            msg['priority'] = str(priority)
        if traceparent is not None:
            msg['traceparent'] = str(traceparent)
        return self._call(msg)['request_id']

    def status(self, request_id: str) -> Dict[str, Any]:
        return self._call({'cmd': protocol.CMD_STATUS,
                           'request_id': request_id})

    def trace(self, request_id: str) -> Dict[str, Any]:
        """The request's assembled span timeline: ``{request_id,
        trace_id, state, events}`` — every recorded span/instant across
        the server's live recorders carrying the request's trace id
        (requires the server to run with a ``trace_out`` base override;
        empty otherwise). Against the fleet router (v1.5) the assembly
        is scatter-gather: router spans plus every attempted backend's
        spans, ts-sorted under one trace_id, each event stamped with a
        ``host`` attr and the additive ``hosts`` field listing the
        contributors."""
        return self._call({'cmd': protocol.CMD_TRACE,
                           'request_id': request_id})

    def wait(self, request_id: str, timeout_s: float = 300.0,
             poll_s: float = 0.05) -> Dict[str, Any]:
        """Block until the request reaches a terminal state; returns the
        final status snapshot. Polls over ONE persistent connection — the
        protocol is request/response per line, and a waiter reconnecting
        20×/s would make the server churn a handler thread per poll."""
        deadline = time.monotonic() + timeout_s
        with self._connect() as conn:
            rfile = conn.makefile('rb')
            while True:
                conn.sendall(protocol.encode(
                    {'cmd': protocol.CMD_STATUS,
                     'request_id': request_id}))
                st = self._read_response(rfile)
                if st['state'] != 'running':
                    return st
                if time.monotonic() >= deadline:
                    raise ServeDeadlineError(
                        f'request {request_id} still {st["state"]} after '
                        f'{timeout_s}s: {st}')
                time.sleep(poll_s)

    def search(self, family: Optional[str] = None,
               vector: Optional[List[float]] = None,
               video_path: Optional[str] = None,
               features: Optional[List[str]] = None,
               k: int = 10,
               timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Query the feature index (v1.3; requires ``index_enabled``).
        By vector: pass ``family`` + ``vector`` → ``{hits: [...]}``. By
        video: pass ``video_path`` + ``features`` → the server extracts
        through the fused path, waits for ingest, and answers
        ``{results: {family: [hits]}}``; each hit is ``{score, video,
        video_sha256, t_ms, key, family}``."""
        msg: Dict[str, Any] = {'cmd': protocol.CMD_SEARCH, 'k': int(k)}
        if family is not None:
            msg['family'] = str(family)
        if vector is not None:
            msg['vector'] = list(vector)
        if video_path is not None:
            msg['video_path'] = str(video_path)
        if features is not None:
            msg['features'] = list(features)
        if timeout_s is not None:
            msg['timeout_s'] = float(timeout_s)
        return self._call(msg)

    def index_status(self) -> Dict[str, Any]:
        """The index section of the metrics document (rows, shards,
        ingest lag, query-program residency) — v1.3."""
        return self._call({'cmd': protocol.CMD_INDEX_STATUS})['index']

    def metrics(self) -> Dict[str, Any]:
        return self._call({'cmd': protocol.CMD_METRICS})['metrics']

    def metrics_prom(self) -> str:
        """The same state as Prometheus text exposition format 0.0.4.
        Against the fleet router (v1.5): the fleet-aggregated exposition
        — every backend's families relabeled ``host=`` plus the
        router's own ``vft_fleet_*`` / ``vft_slo_*`` families."""
        return self._call({'cmd': protocol.CMD_METRICS_PROM})['text']

    def drain(self) -> None:
        """Ask the server to drain (finish queued work, then exit)."""
        self._call({'cmd': protocol.CMD_DRAIN})
