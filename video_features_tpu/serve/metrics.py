"""Live metrics for the extraction service — a view over ONE registry.

The metrics surface is the unified ``obs.metrics`` registry
(PR 4: the flight recorder); this module is the serve-shaped projection
of it. Two renderings of the same state:

  * the JSON document (schema in ``docs/serving.md``) assembled on
    demand from sources that are each already thread-safe — the warm
    pool's counters, the admission gate's depth, per-request latency
    samples, and every pool entry's ``utils.tracing.Tracer`` report
    (stage latencies, batch occupancy, compile ramp);
  * Prometheus text exposition (``prometheus_text``): the same values
    as ``vft_*`` families — counters/histogram straight off the
    registry, point-in-time document values mirrored into gauges — for
    the ``metrics_prom`` socket command and the ``<path>.prom`` file
    mirror.

Both are exposed on the socket and — when ``serve_metrics_path`` is set
— as atomically rewritten files (``utils.output.atomic_write``: a
scraper never reads a torn document).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from video_features_tpu.obs.metrics import MetricsRegistry
from video_features_tpu.utils.tracing import merge_reports

# bounded latency window: p50/p99 over the most recent completions, not
# an unbounded all-time list (a week-long server would otherwise grow
# without bound and average away regressions). The Prometheus histogram
# alongside is cumulative-since-start by design — rate() windows it.
LATENCY_WINDOW = 1024

# counter key → (Prometheus family, labels): request-level outcomes and
# video-level outcomes are separate families
# thread-discipline declaration (vft-lint): write-once constant — every
# RequestStats reads it, nothing mutates it after import
_LOCKED_BY = {'_COUNTER_SERIES': 'immutable'}
_COUNTER_SERIES = {
    'submitted': ('vft_serve_requests_total', {'outcome': 'submitted'}),
    'completed': ('vft_serve_requests_total', {'outcome': 'completed'}),
    'failed': ('vft_serve_requests_total', {'outcome': 'failed'}),
    'rejected': ('vft_serve_requests_total', {'outcome': 'rejected'}),
    'expired_videos': ('vft_serve_videos_total', {'outcome': 'expired'}),
    'cached_videos': ('vft_serve_videos_total', {'outcome': 'cached'}),
}


class RequestStats:
    """Thread-safe request counters + completion-latency window, backed
    by an ``obs.metrics`` registry (one per server instance, so several
    servers in one process never bleed counts into each other)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self._lock = threading.Lock()
        self._counters = {
            key: self.registry.counter(
                family, 'request/video outcomes by type', labels=labels)
            for key, (family, labels) in _COUNTER_SERIES.items()}
        self._latency_hist = self.registry.histogram(
            'vft_serve_request_latency_seconds',
            'request completion latency (admission to terminal state)')
        self._latencies: List[float] = []

    def bump(self, key: str, n: int = 1) -> None:
        self._counters[key].inc(n)

    def observe_latency(self, seconds: float) -> None:
        self._latency_hist.observe(float(seconds))
        with self._lock:
            self._latencies.append(float(seconds))
            if len(self._latencies) > LATENCY_WINDOW:
                del self._latencies[:-LATENCY_WINDOW]

    def snapshot(self) -> Dict[str, Any]:
        counts = {key: int(c.value) for key, c in self._counters.items()}
        with self._lock:
            lat = list(self._latencies)
        out: Dict[str, Any] = {'requests': counts}
        if lat:
            out['latency'] = {
                'count': len(lat),
                'p50_s': round(float(np.percentile(lat, 50)), 4),
                'p99_s': round(float(np.percentile(lat, 99)), 4),
                'max_s': round(max(lat), 4),
            }
        else:
            out['latency'] = {'count': 0, 'p50_s': None, 'p99_s': None,
                              'max_s': None}
        return out


def build_metrics(started_at: float,
                  queue_depth: int,
                  queue_capacity: int,
                  draining: bool,
                  pool_stats: Dict[str, Any],
                  request_stats: RequestStats,
                  stage_reports: Dict[str, Dict],
                  cache_stats: Optional[Dict[str, Any]] = None,
                  inflight_batches: int = 0,
                  farm_stats: Optional[Dict[str, Any]] = None,
                  ingress_stats: Optional[Dict[str, Any]] = None,
                  trace_stats: Optional[Dict[str, Any]] = None,
                  watchdog_stats: Optional[Dict[str, Any]] = None,
                  aot_stats: Optional[Dict[str, Any]] = None,
                  index_stats: Optional[Dict[str, Any]] = None,
                  slo_stats: Optional[Dict[str, Any]] = None,
                  ) -> Dict[str, Any]:
    """Assemble the one metrics document. ``stage_reports`` maps a
    human-readable pool-entry label → that entry's ``Tracer.report()``;
    the aggregate view merges them (``tracing.merge_reports``).
    ``cache_stats`` is the merged content-addressed feature-cache view
    (``cache.store.merge_cache_stats`` over every cache dir requests have
    named) — always present in the document so scrapers see hit/miss/
    bytes-saved counters next to the warm-pool hit rate even before the
    first cache-enabled request. ``farm_stats`` is the merged decode-farm
    view (``farm.merge_farm_stats`` over every warm worker's farm) —
    likewise always present (all-zero before the first farm-backed
    request)."""
    doc: Dict[str, Any] = {
        'uptime_s': round(time.monotonic() - started_at, 3),
        'queue': {'depth': queue_depth, 'capacity': queue_capacity,
                  'draining': draining},
        'warm_pool': pool_stats,
        # async device loop: dispatched-but-unmaterialized device batches
        # across every warm worker (0 when idle or fully synchronous)
        'inflight_batches': int(inflight_batches),
    }
    if cache_stats is None:
        from video_features_tpu.cache.store import merge_cache_stats
        cache_stats = merge_cache_stats(())
    doc['cache'] = cache_stats
    if farm_stats is None:
        from video_features_tpu.farm.farm import merge_farm_stats
        farm_stats = merge_farm_stats(())
    doc['farm'] = farm_stats
    # persistent executable store (aot/): merged store counters across
    # every store live workers were built against, plus how many
    # programs took each path (loaded from disk vs compiled) — always
    # present (all-zero without aot_enabled) so scrapers see one stable
    # schema; builds_compiled == 0 with programs_loaded > 0 is the
    # "zero cold start" reading
    if aot_stats is None:
        from video_features_tpu.aot.store import merge_exec_stats
        aot_stats = merge_exec_stats(())
        aot_stats['programs_loaded'] = 0
        aot_stats['programs_compiled'] = 0
    doc['aot'] = aot_stats
    # the network front door's view: per-tenant request/shed counters,
    # live-session + connection gauges (ingress/gateway.stats()) —
    # always present, {'enabled': False} on a loopback-only server, so
    # scrapers see one stable schema
    # feature-index view (index/): rows/shards/ingest-lag from the
    # serve-side ingest worker plus query counters — always present,
    # {'enabled': False} without index_enabled, so scrapers see one
    # stable schema; ingest_lag_bytes == 0 means the index has folded
    # in every published cache object
    doc['index'] = (index_stats if index_stats is not None
                    else {'enabled': False, 'rows_live': 0, 'rows_dead': 0,
                          'shards': 0, 'rows_indexed': 0, 'rows_dropped': 0,
                          'ingest_lag_bytes': 0, 'queries': 0})
    doc['ingress'] = (ingress_stats if ingress_stats is not None
                      else {'enabled': False, 'requests_total': 0,
                            'shed_total': 0, 'live_sessions': 0,
                            'open_connections': 0, 'tenants': {}})
    # structured-event accounting (obs/events): lifetime counts per
    # (level, subsystem) — the vft_events_total mirror's source; always
    # present so scrapers see a stable schema
    from video_features_tpu.obs.events import event_counts
    counts = {f'{level}/{subsystem}': n
              for (level, subsystem), n in sorted(event_counts().items())}
    doc['events'] = {'total': sum(counts.values()), 'counts': counts}
    # span-ring view (vft-flight): live recorders + events lost to ring
    # wrap — today only visible in the Chrome-trace footer, invisible
    # to scrapers without this
    doc['trace'] = (trace_stats if trace_stats is not None
                    else {'recorders': 0, 'events_dropped': 0})
    # stall watchdog (obs/watchdog): the progress-ledger view, or the
    # stable disabled shape on servers without watchdog_stall_s
    doc['watchdog'] = (watchdog_stats if watchdog_stats is not None
                       else {'enabled': False, 'stalls_total': 0,
                             'workers': {}})
    # SLO burn rates (obs/slo): objectives + per-window burn + alert
    # states, or the stable disabled shape without slo_* knobs
    if slo_stats is not None:
        doc['slo'] = slo_stats
    else:
        from video_features_tpu.obs.slo import disabled_stats
        doc['slo'] = disabled_stats()
    doc.update(request_stats.snapshot())
    doc['stages'] = {label: rep for label, rep in stage_reports.items()}
    doc['stages_merged'] = merge_reports(stage_reports.values())
    return doc


def prometheus_text(doc: Dict[str, Any],
                    registry: MetricsRegistry) -> str:
    """Render the metrics state as Prometheus text exposition 0.0.4.

    Counters and the latency histogram come straight off ``registry``
    (``RequestStats`` writes them); the document's point-in-time values
    — queue depth, warm-pool and cache counters, the merged stage table
    — mirror into gauges on the same registry first, so one ``render``
    emits the whole surface."""
    g = registry.gauge
    g('vft_serve_uptime_seconds',
      'seconds since server start').set(doc.get('uptime_s', 0.0))
    q = doc.get('queue') or {}
    g('vft_serve_queue_depth',
      'videos queued or in flight').set(q.get('depth', 0))
    g('vft_serve_queue_capacity',
      'admission bound (serve_queue_depth)').set(q.get('capacity', 0))
    g('vft_serve_draining',
      '1 while draining, else 0').set(1 if q.get('draining') else 0)
    g('vft_inflight_batches',
      'device batches dispatched but not yet materialized (async '
      'device loop)').set(doc.get('inflight_batches', 0))
    for key, value in (doc.get('warm_pool') or {}).items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            g(f'vft_warm_pool_{key}',
              'warm extractor pool accounting').set(value)
    for dev, count in (doc.get('warm_pool') or {}
                       ).get('device_residents', {}).items():
        # placement-aware pool: how many warm entries each chip carries
        g('vft_device_resident_entries',
          'warm-pool entries resident per device',
          labels={'device': dev}).set(count)
    for dev, nbytes in (doc.get('warm_pool') or {}
                        ).get('device_resident_bytes', {}).items():
        # REAL per-chip residency: a bf16 fast-lane entry counts its
        # actual ~half-size params footprint, not '1 entry'
        g('vft_device_resident_bytes',
          'warm-pool params bytes resident per device',
          labels={'device': dev}).set(nbytes)
    for key, value in (doc.get('cache') or {}).items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            g(f'vft_cache_{key}',
              'content-addressed feature cache accounting').set(value)
    for key, value in (doc.get('farm') or {}).items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            g(f'vft_farm_{key}',
              'decode farm accounting (merged across warm workers)'
              ).set(value)
    for key, value in (doc.get('aot') or {}).items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            # vft_aot_programs_loaded vs vft_aot_programs_compiled is
            # the zero-cold-start dashboard pair (docs/serving.md)
            g(f'vft_aot_{key}',
              'persistent executable store accounting (merged across '
              'warm workers)').set(value)
    for key, value in (doc.get('index') or {}).items():
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            # point-in-time mirrors; the registered vft_index_*_total
            # counters and latency histogram render off the registry
            # directly (IndexService registers them at construction)
            g(f'vft_index_{key}',
              'sharded feature-index accounting (ingest worker + '
              'query engine)').set(value)
    # monotonic mirrors (counter semantics, hence _total names): the
    # document carries lifetime totals; the registry counter advances by
    # the delta so repeated renders never double-count and a recorder
    # aging out of the bounded deque (sum dips) never decrements
    def _mirror_counter(name: str, help_text: str, total: float,
                        labels: Optional[Dict[str, str]] = None) -> None:
        c = registry.counter(name, help_text, labels=labels)
        delta = float(total) - c.value
        if delta > 0:
            c.inc(delta)

    for key, n in ((doc.get('events') or {}).get('counts') or {}).items():
        level, _, subsystem = key.partition('/')
        _mirror_counter('vft_events_total',
                        'structured events by level and subsystem '
                        '(obs/events)', n,
                        labels={'level': level,
                                'subsystem': subsystem or 'core'})
    _mirror_counter('vft_trace_events_dropped_total',
                    'span-ring events lost to ring-buffer wrap across '
                    'the live recorders', (doc.get('trace') or {}
                                           ).get('events_dropped', 0))
    wd = doc.get('watchdog') or {}
    g('vft_watchdog_enabled',
      '1 when the stall watchdog is armed, else 0').set(
          1 if wd.get('enabled') else 0)
    for stage, rep in (doc.get('stages_merged') or {}).items():
        # gauge family names deliberately avoid the _total suffix
        # (reserved for counter semantics): these mirror a point-in-time
        # document, and tracer resets mean they are not monotonic
        labels = {'stage': stage}
        g('vft_stage_seconds', 'merged stage wall time',
          labels=labels).set(rep.get('total_s', 0.0))
        g('vft_stage_calls', 'merged stage call count',
          labels=labels).set(rep.get('count', 0))
        if rep.get('occupancy') is not None:
            g('vft_stage_occupancy',
              'valid batch slots / all slots for the stage',
              labels=labels).set(rep['occupancy'])
        for dev, drec in (rep.get('occ_device') or {}).items():
            # mesh-sharded batches: the same family grows a device
            # label, one series per chip (aggregate stays label-free)
            g('vft_stage_occupancy',
              'valid batch slots / all slots for the stage',
              labels={'stage': stage, 'device': dev}
              ).set(drec.get('occupancy', 0.0))
    return registry.render()


def write_metrics_file(path: Optional[str], doc: Dict[str, Any],
                       prom_text: Optional[str] = None) -> None:
    """Atomically mirror the metrics document to ``path`` (no-op if
    unset) and — when given — the Prometheus rendering to
    ``<path>.prom`` (node_exporter textfile-collector friendly).
    Failures are swallowed — metrics mirroring must never take down the
    serving loop."""
    if not path:
        return
    from video_features_tpu.utils.output import atomic_write
    try:
        atomic_write(path, lambda f: f.write(
            json.dumps(doc, sort_keys=True).encode('utf-8')))
        if prom_text is not None:
            atomic_write(path + '.prom',
                         lambda f: f.write(prom_text.encode('utf-8')))
    except OSError:
        pass
