"""Live metrics for the extraction service.

One JSON document (schema in ``docs/serving.md``) assembled on demand
from sources that are each already thread-safe — the warm pool's
counters, the admission gate's depth, per-request latency samples, and
every pool entry's ``utils.tracing.Tracer`` report (stage latencies,
batch occupancy, compile ramp). Exposed two ways: the ``metrics`` socket
command, and — when ``serve_metrics_path`` is set — an atomically
rewritten JSON file (``utils.output.atomic_write``: a scraper never
reads a torn document).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from video_features_tpu.utils.tracing import merge_reports

# bounded latency window: p50/p99 over the most recent completions, not
# an unbounded all-time list (a week-long server would otherwise grow
# without bound and average away regressions)
LATENCY_WINDOW = 1024


class RequestStats:
    """Thread-safe request counters + completion-latency window."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counts = {'submitted': 0, 'completed': 0, 'failed': 0,
                       'rejected': 0, 'expired_videos': 0,
                       # videos answered from the content-addressed
                       # feature cache (pre-admission or in-worker hits)
                       'cached_videos': 0}
        self._latencies: List[float] = []

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counts[key] += n

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(float(seconds))
            if len(self._latencies) > LATENCY_WINDOW:
                del self._latencies[:-LATENCY_WINDOW]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = dict(self.counts)
            lat = list(self._latencies)
        out: Dict[str, Any] = {'requests': counts}
        if lat:
            out['latency'] = {
                'count': len(lat),
                'p50_s': round(float(np.percentile(lat, 50)), 4),
                'p99_s': round(float(np.percentile(lat, 99)), 4),
                'max_s': round(max(lat), 4),
            }
        else:
            out['latency'] = {'count': 0, 'p50_s': None, 'p99_s': None,
                              'max_s': None}
        return out


def build_metrics(started_at: float,
                  queue_depth: int,
                  queue_capacity: int,
                  draining: bool,
                  pool_stats: Dict[str, Any],
                  request_stats: RequestStats,
                  stage_reports: Dict[str, Dict],
                  cache_stats: Optional[Dict[str, Any]] = None,
                  ) -> Dict[str, Any]:
    """Assemble the one metrics document. ``stage_reports`` maps a
    human-readable pool-entry label → that entry's ``Tracer.report()``;
    the aggregate view merges them (``tracing.merge_reports``).
    ``cache_stats`` is the merged content-addressed feature-cache view
    (``cache.store.merge_cache_stats`` over every cache dir requests have
    named) — always present in the document so scrapers see hit/miss/
    bytes-saved counters next to the warm-pool hit rate even before the
    first cache-enabled request."""
    doc: Dict[str, Any] = {
        'uptime_s': round(time.monotonic() - started_at, 3),
        'queue': {'depth': queue_depth, 'capacity': queue_capacity,
                  'draining': draining},
        'warm_pool': pool_stats,
    }
    if cache_stats is None:
        from video_features_tpu.cache.store import merge_cache_stats
        cache_stats = merge_cache_stats(())
    doc['cache'] = cache_stats
    doc.update(request_stats.snapshot())
    doc['stages'] = {label: rep for label, rep in stage_reports.items()}
    doc['stages_merged'] = merge_reports(stage_reports.values())
    return doc


def write_metrics_file(path: Optional[str], doc: Dict[str, Any]) -> None:
    """Atomically mirror the metrics document to ``path`` (no-op if unset).
    Failures are swallowed — metrics mirroring must never take down the
    serving loop."""
    if not path:
        return
    from video_features_tpu.utils.output import atomic_write
    try:
        atomic_write(path, lambda f: f.write(
            json.dumps(doc, sort_keys=True).encode('utf-8')))
    except OSError:
        pass
