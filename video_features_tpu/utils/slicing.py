"""Temporal stack slicing (reference utils/utils.py:65-74)."""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


def form_slices(size: int, stack_size: int, step_size: int) -> List[Tuple[int, int]]:
    """Sliding-window (start, end) index pairs; full stacks only (floor).

    Partial final stacks are dropped — the reference does the same for
    i3d/r21d/s3d and parity requires reproducing it.
    """
    full_stack_num = (size - stack_size) // step_size + 1
    return [(i * step_size, i * step_size + stack_size) for i in range(max(full_stack_num, 0))]


def stack_indices(size: int, stack_size: int, step_size: int) -> np.ndarray:
    """All stack windows as one gather-index array of shape (num_stacks, stack_size).

    TPU-first counterpart of :func:`form_slices`: instead of a Python loop of
    slices, one integer array drives a single vectorized ``frames[idx]`` gather
    that produces the whole (num_stacks, stack_size, ...) clip batch at once.
    """
    slices = form_slices(size, stack_size, step_size)
    if not slices:
        return np.zeros((0, stack_size), dtype=np.int32)
    starts = np.array([s for s, _ in slices], dtype=np.int32)
    return starts[:, None] + np.arange(stack_size, dtype=np.int32)[None, :]
