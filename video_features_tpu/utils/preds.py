"""Top-5 prediction printing (reference utils/utils.py:21-54 surface).

The three public label maps (Kinetics-400, ImageNet-1k/-21k — the same
files the reference bundles as utils/*_label_map.txt) ship as package data
in ``utils/label_maps/``, so class names work on air-gapped hosts with no
env var or reference checkout. ``$VFT_LABEL_MAP_DIR`` still takes
precedence for user-refreshed maps (tools/fetch_label_maps.py), and when
nothing resolves, indices are printed instead of failing.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import List, Optional, Union

import numpy as np

_DATASET_TO_FILE = {
    'kinetics': 'K400_label_map.txt',
    'imagenet1k': 'IN1K_label_map.txt',
    'imagenet21k': 'IN21K_label_map.txt',
}

def _search_dirs() -> List[str]:
    # read the env var per call so `os.environ['VFT_LABEL_MAP_DIR'] = ...`
    # after import still takes effect; the bundled package copies are the
    # always-available fallback
    return [
        os.environ.get('VFT_LABEL_MAP_DIR', ''),
        str(Path(__file__).parent / 'label_maps'),
    ]


def load_label_map(dataset: str) -> Optional[List[str]]:
    fname = _DATASET_TO_FILE.get(dataset)
    if fname is None:
        return None
    for d in _search_dirs():
        if d and (Path(d) / fname).exists():
            with open(Path(d) / fname) as f:
                return [line.strip() for line in f]
    return None


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def show_predictions_on_dataset(logits: np.ndarray,
                                dataset: Union[str, List[str]], k: int = 5) -> None:
    """Print a top-k table of logits/probabilities/labels per batch row."""
    logits = np.asarray(logits)
    if isinstance(dataset, str):
        classes = load_label_map(dataset)
    else:
        classes = list(dataset)
    probs = softmax(logits)
    top_idx = np.argsort(-probs, axis=-1)[:, :k]
    for b in range(logits.shape[0]):
        # vft-lint: ok=stdout-purity — show_pred's top-k table IS the
        # deliberate stdout surface of this debug mode (reference parity);
        # sanity_check keeps show_pred off the packed/stream paths
        print('  Logits | Prob. | Label ')
        for idx in top_idx[b]:
            label = classes[idx] if classes and idx < len(classes) else f'class_{idx}'
            # vft-lint: ok=stdout-purity — show_pred table row
            print(f'{logits[b, idx]:8.3f} | {probs[b, idx]:.3f} | {label}')
        print()  # vft-lint: ok=stdout-purity — show_pred table spacer
