"""CLIP byte-pair-encoding tokenizer (OpenAI scheme).

Re-implementation of the algorithm behind the reference's vendored tokenizer
(reference models/clip/clip_src/simple_tokenizer.py, 132 LoC): reversible
byte→unicode alphabet, greedy lowest-rank BPE merges with a ``</w>``
word-end marker, and the `<|startoftext|>`/`<|endoftext|>` specials.

The merge table (``bpe_simple_vocab_16e6.txt.gz``) is DATA, not code — it is
looked up at runtime: ``$VFT_CLIP_BPE`` first, then the reference checkout.
Tokenization only powers zero-shot ``show_pred``; feature extraction never
needs it, so a missing vocab degrades gracefully (see extract/clip.py).
"""
from __future__ import annotations

import gzip
import html
import os
from functools import lru_cache
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

CONTEXT_LENGTH = 77
VOCAB_SIZE = 49408

_SEARCH_PATHS = [
    os.environ.get('VFT_CLIP_BPE', ''),
    '/root/reference/models/clip/clip_src/bpe_simple_vocab_16e6.txt.gz',
]


def find_bpe_vocab() -> Optional[str]:
    for p in _SEARCH_PATHS:
        if p and Path(p).exists():
            return p
    return None


@lru_cache()
def bytes_to_unicode() -> Dict[int, str]:
    """Reversible byte → printable-unicode map (the GPT-2/CLIP alphabet):
    printable ranges map to themselves, the rest shift past U+0100."""
    bs = (list(range(ord('!'), ord('~') + 1))
          + list(range(ord('¡'), ord('¬') + 1))
          + list(range(ord('®'), ord('ÿ') + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def get_pairs(word: Tuple[str, ...]):
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


def _basic_clean(text: str) -> str:
    try:  # ftfy fixes mojibake; optional, matches reference behavior w/o it
        import ftfy
        text = ftfy.fix_text(text)
    except ImportError:
        pass
    return html.unescape(html.unescape(text)).strip()


def _whitespace_clean(text: str) -> str:
    return ' '.join(text.split())


class SimpleTokenizer:
    """Greedy BPE with the OpenAI CLIP merge table."""

    def __init__(self, bpe_path: Optional[str] = None) -> None:
        bpe_path = bpe_path or find_bpe_vocab()
        if bpe_path is None:
            raise FileNotFoundError(
                'CLIP BPE vocab not found; set $VFT_CLIP_BPE to '
                'bpe_simple_vocab_16e6.txt.gz')
        self.byte_encoder = bytes_to_unicode()
        merges = gzip.open(bpe_path).read().decode('utf-8').split('\n')
        # header line + the first 49152-256-2 merges, per OpenAI's slice
        merges = merges[1:49152 - 256 - 2 + 1]
        merge_pairs = [tuple(m.split()) for m in merges]
        vocab = list(self.byte_encoder.values())
        vocab += [v + '</w>' for v in vocab]
        vocab += [''.join(m) for m in merge_pairs]
        vocab += ['<|startoftext|>', '<|endoftext|>']
        self.encoder = {tok: i for i, tok in enumerate(vocab)}
        self.decoder = {i: tok for tok, i in self.encoder.items()}
        self.bpe_ranks = {pair: i for i, pair in enumerate(merge_pairs)}
        self.cache = {'<|startoftext|>': '<|startoftext|>',
                      '<|endoftext|>': '<|endoftext|>'}
        self._pattern = self._compile_pattern()

    @staticmethod
    def _compile_pattern():
        try:
            import regex
            return regex.compile(
                r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"""
                r"""|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""", regex.IGNORECASE)
        except ImportError:
            import re
            # stdlib emulation of the unicode classes: letters \p{L} ==
            # [^\W\d_] (word chars minus digits minus underscore), \p{N} ≈
            # \d, and the punctuation run [^\s\p{L}\p{N}]+ == ([^\s\w]|_)+
            # (non-word-non-space, plus underscore which \w wrongly keeps).
            return re.compile(
                r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"""
                r"""|[^\W\d_]+|\d|(?:[^\s\w]|_)+""", re.IGNORECASE)

    def bpe(self, token: str) -> str:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token[:-1]) + (token[-1] + '</w>',)
        pairs = get_pairs(word)
        if not pairs:
            return token + '</w>'
        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float('inf')))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if (word[i] == first and i < len(word) - 1
                        and word[i + 1] == second):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = get_pairs(word)
        out = ' '.join(word)
        self.cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        text = _whitespace_clean(_basic_clean(text)).lower()
        bpe_tokens: List[int] = []
        for token in self._pattern.findall(text):
            token = ''.join(self.byte_encoder[b] for b in token.encode('utf-8'))
            bpe_tokens.extend(self.encoder[t] for t in self.bpe(token).split(' '))
        return bpe_tokens


def tokenize(texts, tokenizer: Optional[SimpleTokenizer] = None,
             context_length: int = CONTEXT_LENGTH) -> np.ndarray:
    """List of strings → (N, 77) int32 token matrix (reference clip.py:200-240
    semantics: SOT + bpe + EOT, zero-padded; over-long inputs error)."""
    if isinstance(texts, str):
        texts = [texts]
    tokenizer = tokenizer or SimpleTokenizer()
    sot = tokenizer.encoder['<|startoftext|>']
    eot = tokenizer.encoder['<|endoftext|>']
    result = np.zeros((len(texts), context_length), np.int32)
    for i, text in enumerate(texts):
        tokens = [sot] + tokenizer.encode(text) + [eot]
        if len(tokens) > context_length:
            raise RuntimeError(
                f'Input {text!r} is too long for context length {context_length}')
        result[i, :len(tokens)] = tokens
    return result
