"""Per-stage timing and JAX profiler hooks.

The reference has no tracing/profiling surface at all — only tqdm progress
and prints (SURVEY.md §5.1; reference main.py:2,47). On TPU the pipeline is
host-decode-bound long before it is FLOPs-bound, so knowing how wall time
splits across decode / preprocess / host→device+model / save is the first
profiling question. This module provides:

  * ``Tracer`` — a thread-safe accumulator of named stage timings. Stages
    are timed with ``with tracer.stage('decode'): ...`` or by wrapping an
    iterator (``tracer.wrap_iter('decode', loader)`` times each ``next()``
    call, which is where streaming decode work actually happens — including
    on the prefetch producer thread).
  * ``NULL_TRACER`` — a disabled singleton; instrumentation sites cost two
    attribute loads and a truthiness check when profiling is off.
  * ``jax_profiler_trace(dir)`` — context manager around
    ``jax.profiler.trace`` for XLA/TPU-level traces viewable in
    TensorBoard/Perfetto, gated so importing this module never imports jax.

Enable per-run with the ``profile: true`` config key (any extractor); each
video then prints a stage table after extraction. ``profile_dir`` addition-
ally captures a jax profiler trace of the whole run.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterable, Iterator, List, Optional


class _StageStat:
    __slots__ = ('count', 'total_s', 'max_s')

    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def add(self, dt: float) -> None:
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt


class Tracer:
    """Thread-safe named-stage wall-time accumulator."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._stats: Dict[str, _StageStat] = {}
        self._order: List[str] = []

    # -- recording -----------------------------------------------------------

    def add(self, name: str, dt: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = _StageStat()
                self._order.append(name)
            stat.add(dt)

    @contextmanager
    def stage(self, name: str):
        """Time a block under ``name`` (no-op when disabled)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def wrap_iter(self, name: str, iterable: Iterable) -> Iterator:
        """Yield from ``iterable``, timing each ``next()`` under ``name``.

        Streaming decoders do their work inside ``next()``; wrapping the
        iterator (before any prefetch thread) therefore times decode on the
        thread that actually runs it.
        """
        if not self.enabled:
            yield from iterable
            return
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            finally:
                self.add(name, time.perf_counter() - t0)
            yield item

    # -- reporting -----------------------------------------------------------

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {'count': s.count, 'total_s': s.total_s,
                       'mean_s': s.total_s / max(s.count, 1), 'max_s': s.max_s}
                for name, s in self._stats.items()
            }

    def summary(self) -> str:
        """Human-readable stage table, ordered by first occurrence."""
        # one lock acquisition for both stats and order: a concurrent add()
        # (e.g. a lingering prefetch thread) must not desync them
        with self._lock:
            order = list(self._order)
            rep = {
                name: {'count': s.count, 'total_s': s.total_s,
                       'mean_s': s.total_s / max(s.count, 1), 'max_s': s.max_s}
                for name, s in self._stats.items()
            }
        if not rep:
            return '(no stages recorded)'
        total = sum(r['total_s'] for r in rep.values())
        width = max(len(n) for n in order)
        lines = [f'{"stage".ljust(width)} | count |  total s |   mean ms | share']
        for name in order:
            r = rep[name]
            share = r['total_s'] / total * 100 if total else 0.0
            lines.append(
                f'{name.ljust(width)} | {r["count"]:5d} | {r["total_s"]:8.3f} '
                f'| {r["mean_s"] * 1e3:9.2f} | {share:4.1f}%')
        return '\n'.join(lines)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._order.clear()


NULL_TRACER = Tracer(enabled=False)


@contextmanager
def jax_profiler_trace(log_dir: Optional[str]):
    """Capture a jax/XLA profiler trace to ``log_dir`` (None → no-op).

    The trace includes device-side timelines (TPU step traces, XLA op
    breakdowns) viewable with TensorBoard's profile plugin or Perfetto.
    """
    if not log_dir:
        yield
        return
    import jax
    with jax.profiler.trace(str(log_dir)):
        yield
