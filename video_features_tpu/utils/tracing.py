"""Per-stage timing and JAX profiler hooks.

The reference has no tracing/profiling surface at all — only tqdm progress
and prints (SURVEY.md §5.1; reference main.py:2,47). On TPU the pipeline is
host-decode-bound long before it is FLOPs-bound, so knowing how wall time
splits across decode / preprocess / host→device+model / save is the first
profiling question. This module provides:

  * ``Tracer`` — a thread-safe accumulator of named stage timings. Stages
    are timed with ``with tracer.stage('decode'): ...`` or by wrapping an
    iterator (``tracer.wrap_iter('decode', loader)`` times each ``next()``
    call, which is where streaming decode work actually happens — including
    on the prefetch producer thread).
  * ``NULL_TRACER`` — a disabled singleton; instrumentation sites cost two
    attribute loads and a truthiness check when profiling is off.
  * ``jax_profiler_trace(dir)`` — context manager around
    ``jax.profiler.trace`` for XLA/TPU-level traces viewable in
    TensorBoard/Perfetto, gated so importing this module never imports jax.

Enable per-run with the ``profile: true`` config key (any extractor); each
video then prints a stage table after extraction. ``profile_dir`` addition-
ally captures a jax profiler trace of the whole run.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterable, Iterator, List, Optional

# Canonical pipeline stage names — the shared vocabulary across the stage
# table, the span timeline (obs/spans), the serve metrics families
# (vft_stage_*), and bench stage_reports. A stage either appears under
# one of these names everywhere or under its own new name everywhere; in
# particular `model` is DISPATCH + compute-up-to-sync only, and `d2h` is
# the deferred device→host readback + host copy (split out so readback
# can overlap compute without laundering into compute time — the async
# device loop, parallel/packing.py). Pinned by tests/test_obs.py.
STAGES = (
    'decode',             # raw decode (stack families without preprocess)
    'decode+preprocess',  # decode + host transform on the prefetch thread
    'audio_dsp',          # vggish: host-side mel/log-mel DSP on the wav
    'queue_idle',         # serve: blocking waits on an idle request feed
    'pack',               # packed batch assembly (pool flush + np.stack)
    'h2d',                # host→device input transfer (producer thread)
    'model',              # device-step dispatch + compute until the sync
    'd2h',                # deferred device→host readback of step outputs
    'save',               # output materialization (.npy/.pkl writes)
    'cache_lookup',       # content-addressed cache consult
    'cache_publish',      # content-addressed cache publish
)


class _StageStat:
    __slots__ = ('count', 'total_s', 'max_s', 'first_s',
                 'occ_valid', 'occ_capacity', 'occ_device')

    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        # first-call wall time: the pipeline-ramp term (compile + cache
        # warm + prefetch fill) that a batch-major corpus loop pays once
        # instead of once per video
        self.first_s = 0.0
        # batch-slot accounting (add_occupancy): how full the compiled
        # batch actually ran — padded tail slots burn the same device time
        # as real work
        self.occ_valid = 0
        self.occ_capacity = 0
        # per-DEVICE slot accounting for mesh-sharded batches
        # (add_occupancy(..., device=)): device label → [valid, capacity]
        # raw counts, kept SEPARATE from the aggregate above so the two
        # views never double-count (the aggregate is recorded once per
        # batch at the global capacity; each shard's slice lands here)
        self.occ_device: Optional[Dict[str, list]] = None

    def add(self, dt: float) -> None:
        if self.count == 0:
            self.first_s = dt
        self.count += 1
        self.total_s += dt
        if dt > self.max_s:
            self.max_s = dt

    def ramp(self) -> Optional[float]:
        """first-call time over the steady-state mean (None until 2 calls).

        ~1.0 = no ramp; large values = a compile/warm-up wall that a
        longer run (or cross-video packing) amortizes away.
        """
        if self.count < 2:
            return None
        steady = (self.total_s - self.first_s) / (self.count - 1)
        return self.first_s / steady if steady > 0 else None

    def occupancy(self) -> Optional[float]:
        """valid-slot fraction of all batch slots (None if never recorded)."""
        if self.occ_capacity <= 0:
            return None
        return self.occ_valid / self.occ_capacity


class Tracer:
    """Thread-safe named-stage wall-time accumulator.

    With a ``recorder`` (``obs.spans.SpanRecorder``) attached, every
    timed stage ALSO lands as a span event on the flight-recorder
    timeline — the aggregate table and the Perfetto trace are two views
    over the same instrumentation sites. ``attrs`` passed to
    ``stage``/``add`` (video path, request id, batch occupancy) ride on
    the span's ``args``; the aggregate ignores them.
    """

    def __init__(self, enabled: bool = True, recorder=None) -> None:
        self.enabled = enabled
        self.recorder = recorder
        # liveness hook (obs/watchdog.py): called with (stage, worker)
        # on every recorded stage — the stall watchdog's progress ledger
        # rides the SAME instrumentation sites as the table/timeline.
        # ``worker`` is the farm worker index when the site carries one
        # (the ``worker=`` span attr), else None.
        self.progress = None
        self._lock = threading.Lock()
        self._stats: Dict[str, _StageStat] = {}
        self._order: List[str] = []

    # -- recording -----------------------------------------------------------

    def add(self, name: str, dt: float, t0: Optional[float] = None,
            span_pid: Optional[int] = None, span_tid: Optional[int] = None,
            **attrs) -> None:
        """Record ``dt`` seconds under ``name``. ``t0`` (the stage's
        ``time.perf_counter`` start, when the caller knows it) places the
        span on the timeline; without it the span is back-dated from
        now. ``span_pid``/``span_tid`` override the span's recorded
        process/thread identity (cross-process sites: the decode farm
        records spans its workers measured)."""
        if not self.enabled:
            return
        rec = self.recorder
        if rec is not None and rec.enabled:
            if t0 is None:
                t0 = time.perf_counter() - dt
            rec.span(name, t0, t0 + dt, pid=span_pid, tid=span_tid,
                     **attrs)
        progress = self.progress
        if progress is not None:
            try:
                progress(name, attrs.get('worker'))
            except Exception:
                # vft-lint: ok=swallowed-exception — a broken liveness
                # hook must not fail the hot loop it observes
                pass
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = _StageStat()
                self._order.append(name)
            stat.add(dt)

    def add_occupancy(self, name: str, valid: int, capacity: int,
                      device: Optional[str] = None) -> None:
        """Record that a ``capacity``-slot batch under ``name`` carried
        ``valid`` real items (the rest was padding). The summary table then
        reports the stage's aggregate batch occupancy — the fraction of
        compiled-step slots that did useful work.

        With ``device`` given (mesh-sharded packed batches), the counts
        land in the stage's PER-DEVICE map instead of the aggregate: the
        device loop records the aggregate once per batch at the global
        capacity and each shard's slice under its device label, so neither
        view double-counts the other (see ``merge_reports``)."""
        if not self.enabled:
            return
        with self._lock:
            stat = self._stats.get(name)
            if stat is None:
                stat = self._stats[name] = _StageStat()
                self._order.append(name)
            if device is not None:
                if stat.occ_device is None:
                    stat.occ_device = {}
                rec = stat.occ_device.setdefault(str(device), [0, 0])
                rec[0] += int(valid)
                rec[1] += int(capacity)
            else:
                stat.occ_valid += int(valid)
                stat.occ_capacity += int(capacity)

    @contextmanager
    def stage(self, name: str, **attrs):
        """Time a block under ``name`` (no-op when disabled). ``attrs``
        annotate the span on an attached recorder (the aggregate table
        ignores them)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0, t0=t0, **attrs)

    def wrap_iter(self, name: str, iterable: Iterable) -> Iterator:
        """Yield from ``iterable``, timing each ``next()`` under ``name``.

        Streaming decoders do their work inside ``next()``; wrapping the
        iterator (before any prefetch thread) therefore times decode on the
        thread that actually runs it.
        """
        if not self.enabled:
            yield from iterable
            return
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            finally:
                self.add(name, time.perf_counter() - t0, t0=t0)
            yield item

    # -- reporting -----------------------------------------------------------

    @staticmethod
    def _stat_record(s: '_StageStat') -> Dict[str, float]:
        rec = {'count': s.count, 'total_s': s.total_s,
               'mean_s': s.total_s / max(s.count, 1), 'max_s': s.max_s,
               'first_s': s.first_s}
        ramp = s.ramp()
        if ramp is not None:
            rec['ramp'] = ramp
        occ = s.occupancy()
        if occ is not None:
            rec['occupancy'] = occ
            # raw slot counts ride along so reports stay mergeable
            # (merge_reports recomputes aggregate occupancy from these —
            # averaging the derived ratios would weight batches wrongly)
            rec['occ_valid'] = s.occ_valid
            rec['occ_capacity'] = s.occ_capacity
        if s.occ_device:
            # mesh-sharded batches: each device's slot accounting, raw
            # counts + derived ratio (the serve metrics surface renders
            # these as vft_stage_occupancy{device=...})
            rec['occ_device'] = {
                dev: {'occ_valid': v, 'occ_capacity': c,
                      'occupancy': (v / c) if c else 0.0}
                for dev, (v, c) in s.occ_device.items()}
        return rec

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {name: self._stat_record(s)
                    for name, s in self._stats.items()}

    def summary(self) -> str:
        """Human-readable stage table, ordered by first occurrence.

        Beyond the wall-time split, two pipeline-health columns:
        ``occ%`` — aggregate batch occupancy (valid slots / all slots) where
        the stage recorded it (the compiled device step under packed or
        batched loops); ``ramp`` — first-call time over the steady-state
        mean, i.e. the compile/warm-up wall the run amortizes (≈1 = none).
        """
        # one lock acquisition for both stats and order: a concurrent add()
        # (e.g. a lingering prefetch thread) must not desync them
        with self._lock:
            order = list(self._order)
            rep = {name: self._stat_record(s)
                   for name, s in self._stats.items()}
        if not rep:
            return '(no stages recorded)'
        total = sum(r['total_s'] for r in rep.values())
        width = max(len(n) for n in order)
        lines = [f'{"stage".ljust(width)} | count |  total s |   mean ms '
                 f'| share |  occ% |   ramp']
        for name in order:
            r = rep[name]
            share = r['total_s'] / total * 100 if total else 0.0
            occ = (f'{r["occupancy"] * 100:5.1f}'
                   if 'occupancy' in r else '    -')
            ramp = f'{r["ramp"]:6.1f}' if 'ramp' in r else '     -'
            lines.append(
                f'{name.ljust(width)} | {r["count"]:5d} | {r["total_s"]:8.3f} '
                f'| {r["mean_s"] * 1e3:9.2f} | {share:4.1f}% | {occ} | {ramp}')
        return '\n'.join(lines)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._order.clear()


NULL_TRACER = Tracer(enabled=False)


def merge_reports(reports: Iterable[Dict[str, Dict[str, float]]]
                  ) -> Dict[str, Dict[str, float]]:
    """Combine several ``Tracer.report()`` dicts into one aggregate table.

    The serve metrics endpoint exposes one fleet-wide stage view across
    every warm-pool entry's tracer: counts/totals sum, ``max_s`` maxes,
    ``first_s`` keeps the worst cold-start, occupancy recombines from the
    raw slot counts. ``ramp`` is per-tracer by construction (first call vs
    ITS steady state) and is dropped rather than faked.

    Per-device slot accounting (``occ_device`` — mesh-sharded packed
    batches) merges DEVICE-WISE, each device's raw counts summing with
    the same device's counts from other reports. The per-device counts
    are deliberately NEVER folded into the flat ``occ_valid`` /
    ``occ_capacity``: the aggregate is already recorded once per batch
    at the global capacity, so adding the shard slices again would
    double-count valid slots against per-shard capacities and push the
    merged occupancy past 100% (regression-pinned by
    tests/test_mesh_packed.py).
    """
    merged: Dict[str, Dict[str, float]] = {}
    for rep in reports:
        for name, r in rep.items():
            m = merged.setdefault(name, {
                'count': 0, 'total_s': 0.0, 'max_s': 0.0, 'first_s': 0.0,
            })
            m['count'] += r.get('count', 0)
            m['total_s'] += r.get('total_s', 0.0)
            m['max_s'] = max(m['max_s'], r.get('max_s', 0.0))
            m['first_s'] = max(m['first_s'], r.get('first_s', 0.0))
            if 'occ_capacity' in r:
                m['occ_valid'] = m.get('occ_valid', 0) + r['occ_valid']
                m['occ_capacity'] = (m.get('occ_capacity', 0)
                                     + r['occ_capacity'])
            for dev, d in (r.get('occ_device') or {}).items():
                by_dev = m.setdefault('occ_device', {})
                md = by_dev.setdefault(dev, {'occ_valid': 0,
                                             'occ_capacity': 0})
                md['occ_valid'] += d.get('occ_valid', 0)
                md['occ_capacity'] += d.get('occ_capacity', 0)
    for m in merged.values():
        m['mean_s'] = m['total_s'] / max(m['count'], 1)
        if m.get('occ_capacity'):
            m['occupancy'] = m['occ_valid'] / m['occ_capacity']
        for md in (m.get('occ_device') or {}).values():
            md['occupancy'] = (md['occ_valid'] / md['occ_capacity']
                               if md['occ_capacity'] else 0.0)
    return merged


def round_report(report: Dict[str, Dict[str, float]],
                 ndigits: int = 6) -> Dict[str, Dict[str, float]]:
    """A ``Tracer.report()`` with floats rounded for compact JSON
    embedding (bench ``stage_reports``, worklist records) — one
    serializer so every embedded report rounds identically."""
    def _round(v):
        if isinstance(v, float):
            return round(v, ndigits)
        if isinstance(v, dict):             # occ_device's nested records
            return {k: _round(x) for k, x in v.items()}
        return v

    return {name: {k: _round(v) for k, v in rec.items()}
            for name, rec in report.items()}


@contextmanager
def jax_profiler_trace(log_dir: Optional[str]):
    """Capture a jax/XLA profiler trace to ``log_dir`` (None → no-op).

    The trace includes device-side timelines (TPU step traces, XLA op
    breakdowns) viewable with TensorBoard's profile plugin or Perfetto.
    """
    if not log_dir:
        yield
        return
    import jax
    with jax.profiler.trace(str(log_dir)):
        yield
