"""Output pathing & serialization (reference utils/utils.py:56-63,252-262)."""
from __future__ import annotations

import os
import pickle
from pathlib import Path
from typing import Any

import numpy as np


def make_path(output_root: str, video_path: str, output_key: str, ext: str) -> str:
    """``<out>/<stem><ext>`` for key 'rgb', else ``<out>/<stem>_<key><ext>``.

    The no-suffix 'rgb' special case is the fork's output contract for the
    concatenated I3D feature (reference utils/utils.py:56-63).
    """
    stem = Path(video_path).stem
    fname = f'{stem}{ext}' if output_key == 'rgb' else f'{stem}_{output_key}{ext}'
    return os.path.join(output_root, fname)


def load_numpy(fpath: str) -> np.ndarray:
    return np.load(fpath)


def write_numpy(fpath: str, value: Any) -> None:
    np.save(fpath, value)


def load_pickle(fpath: str) -> Any:
    with open(fpath, 'rb') as f:
        return pickle.load(f)


def write_pickle(fpath: str, value: Any) -> None:
    with open(fpath, 'wb') as f:
        pickle.dump(value, f)


ACTION_TO_EXT = {'save_numpy': '.npy', 'save_pickle': '.pkl'}
ACTION_TO_SAVE = {'save_numpy': write_numpy, 'save_pickle': write_pickle}
ACTION_TO_LOAD = {'save_numpy': load_numpy, 'save_pickle': load_pickle}
