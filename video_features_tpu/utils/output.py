"""Output pathing & serialization (reference utils/utils.py:56-63,252-262).

Writes are ATOMIC: same-directory tmp file + ``os.replace``. The resume
contract (``is_already_exist`` loads every file) tolerates corruption by
re-extracting, but a killed process or a multihost collision
(``parallel/worklist.py`` assumes collisions are benign) must never leave
a partial file AT THE FINAL PATH — a reader between death and re-extract
would see it, and two writers racing ``os.replace`` each publish a
complete file (last one wins) instead of interleaving.
"""
from __future__ import annotations

import json
import os
import pickle
import tempfile
from pathlib import Path
from typing import Any, Callable, Optional

import numpy as np


class CorruptOutputError(RuntimeError):
    """A saved output file exists but cannot be read back (truncated,
    overwritten, wrong format). Distinct from the raw numpy/pickle
    errors so resume and the feature-cache GC can EVICT-and-re-extract
    on this, while genuine bugs (a missing file, a type error in caller
    code) still surface as themselves."""


def make_path(output_root: str, video_path: str, output_key: str, ext: str) -> str:
    """``<out>/<stem><ext>`` for key 'rgb', else ``<out>/<stem>_<key><ext>``.

    The no-suffix 'rgb' special case is the fork's output contract for the
    concatenated I3D feature (reference utils/utils.py:56-63).
    """
    stem = Path(video_path).stem
    fname = f'{stem}{ext}' if output_key == 'rgb' else f'{stem}_{output_key}{ext}'
    return os.path.join(output_root, fname)


# process umask, read once (os.umask is set-and-return; toggling it per
# write would race other threads). mkstemp creates 0600 files — published
# outputs must keep the 0666&~umask mode plain open() gave before.
_UMASK = os.umask(0)
os.umask(_UMASK)


def atomic_write(fpath: str, write_fn: Callable) -> None:
    """Publish a file atomically: ``write_fn(binary_file)`` fills a tmp
    file in the TARGET's directory (os.replace cannot cross filesystems),
    then one rename makes it visible. Any failure removes the tmp, so
    neither a crash nor an exception strands partial bytes at ``fpath``.
    """
    d = os.path.dirname(fpath) or '.'
    fd, tmp = tempfile.mkstemp(dir=d, prefix=Path(fpath).name + '.',
                               suffix='.tmp')
    try:
        os.fchmod(fd, 0o666 & ~_UMASK)
        with os.fdopen(fd, 'wb') as f:
            write_fn(f)
        os.replace(tmp, fpath)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_numpy(fpath: str) -> np.ndarray:
    # A zero-byte file is np.load's worst case (an opaque EOFError deep in
    # the format reader) and the most common crash artifact — check first.
    if os.path.getsize(fpath) == 0:
        raise CorruptOutputError(f'empty output file: {fpath}')
    try:
        return np.load(fpath)
    except (ValueError, EOFError, OSError, pickle.UnpicklingError) as e:
        # Missing files propagate as-is (a caller bug / race to report,
        # not corruption); anything the format reader chokes on is.
        if isinstance(e, FileNotFoundError):
            raise
        raise CorruptOutputError(
            f'corrupt/truncated .npy file: {fpath} ({e})') from e


def write_numpy(fpath: str, value: Any) -> None:
    # np.save on a FILE OBJECT never appends '.npy', so the tmp name
    # passes through atomic_write untouched
    atomic_write(fpath, lambda f: np.save(f, value))


def load_pickle(fpath: str) -> Any:
    if os.path.getsize(fpath) == 0:
        raise CorruptOutputError(f'empty output file: {fpath}')
    try:
        with open(fpath, 'rb') as f:
            return pickle.load(f)
    except (ValueError, EOFError, OSError, pickle.UnpicklingError,
            AttributeError, ImportError, IndexError) as e:
        if isinstance(e, FileNotFoundError):
            raise
        raise CorruptOutputError(
            f'corrupt/truncated .pkl file: {fpath} ({e})') from e


def write_pickle(fpath: str, value: Any) -> None:
    atomic_write(fpath, lambda f: pickle.dump(value, f))


ACTION_TO_EXT = {'save_numpy': '.npy', 'save_pickle': '.pkl'}
ACTION_TO_SAVE = {'save_numpy': write_numpy, 'save_pickle': write_pickle}
ACTION_TO_LOAD = {'save_numpy': load_numpy, 'save_pickle': load_pickle}


# -- resume fingerprint sidecar ----------------------------------------------
#
# `<stem>_fingerprint.json` next to a video's output files records the
# cache/key.run_fingerprint (config + weights identity) that produced
# them. Resume (BaseExtractor.is_already_exist) keys the skip on it:
# outputs from a DIFFERENT recipe re-extract with a warning instead of
# being silently reused; outputs with no sidecar (pre-fingerprint runs)
# keep the legacy skip.

def fingerprint_path(output_root: str, video_path: str) -> str:
    return make_path(output_root, video_path, 'fingerprint', '.json')


def write_fingerprint(output_root: str, video_path: str,
                      fingerprint: str) -> None:
    atomic_write(
        fingerprint_path(output_root, video_path),
        lambda f: f.write(json.dumps(
            {'fingerprint': fingerprint}).encode('utf-8')))


def read_fingerprint(output_root: str, video_path: str) -> Optional[str]:
    """The recorded fingerprint, or None when absent/unreadable (an
    unreadable sidecar must degrade to 'unknown provenance', not crash
    the resume scan)."""
    try:
        with open(fingerprint_path(output_root, video_path),
                  encoding='utf-8') as f:
            return json.load(f).get('fingerprint')
    except (OSError, ValueError):
        return None
