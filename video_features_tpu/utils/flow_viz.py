"""Optical-flow → RGB visualization (Middlebury color wheel).

Same algorithm family as reference utils/flow_viz.py (131 LoC, based on the
Baker et al. "A Database and Evaluation Methodology for Optical Flow"
color coding): a 55-entry hue wheel (RY/YG/GC/CB/BM/MR segments), flow
vectors normalized by the maximum radius, angle → wheel position, magnitude
→ saturation.
"""
from __future__ import annotations

import numpy as np


def make_colorwheel() -> np.ndarray:
    """(55, 3) uint-range RGB color wheel."""
    RY, YG, GC, CB, BM, MR = 15, 6, 4, 11, 13, 6
    ncols = RY + YG + GC + CB + BM + MR
    wheel = np.zeros((ncols, 3))
    col = 0
    wheel[col:col + RY, 0] = 255
    wheel[col:col + RY, 1] = np.floor(255 * np.arange(RY) / RY)
    col += RY
    wheel[col:col + YG, 0] = 255 - np.floor(255 * np.arange(YG) / YG)
    wheel[col:col + YG, 1] = 255
    col += YG
    wheel[col:col + GC, 1] = 255
    wheel[col:col + GC, 2] = np.floor(255 * np.arange(GC) / GC)
    col += GC
    wheel[col:col + CB, 1] = 255 - np.floor(255 * np.arange(CB) / CB)
    wheel[col:col + CB, 2] = 255
    col += CB
    wheel[col:col + BM, 2] = 255
    wheel[col:col + BM, 0] = np.floor(255 * np.arange(BM) / BM)
    col += BM
    wheel[col:col + MR, 2] = 255 - np.floor(255 * np.arange(MR) / MR)
    wheel[col:col + MR, 0] = 255
    return wheel


def flow_uv_to_colors(u: np.ndarray, v: np.ndarray,
                      convert_to_bgr: bool = False) -> np.ndarray:
    """Per-pixel wheel lookup for normalized flow components in [-1, 1]."""
    wheel = make_colorwheel()
    ncols = wheel.shape[0]
    rad = np.sqrt(u ** 2 + v ** 2)
    angle = np.arctan2(-v, -u) / np.pi
    fk = (angle + 1) / 2 * (ncols - 1)
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = fk - k0

    out = np.zeros(u.shape + (3,), np.uint8)
    for ch in range(3):
        col0 = wheel[k0, ch] / 255.0
        col1 = wheel[k1, ch] / 255.0
        col = (1 - f) * col0 + f * col1
        idx = rad <= 1
        col[idx] = 1 - rad[idx] * (1 - col[idx])   # saturate with magnitude
        col[~idx] = col[~idx] * 0.75               # out-of-range
        out[..., 2 - ch if convert_to_bgr else ch] = np.floor(255 * col)
    return out


def flow_to_image(flow_uv: np.ndarray, clip_flow: float = None,
                  convert_to_bgr: bool = False) -> np.ndarray:
    """(H, W, 2) flow → (H, W, 3) uint8, normalized by the max radius."""
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, 'expected (H, W, 2) flow'
    if clip_flow is not None:
        flow_uv = np.clip(flow_uv, 0, clip_flow)
    u, v = flow_uv[..., 0], flow_uv[..., 1]
    rad_max = np.sqrt(u ** 2 + v ** 2).max()
    eps = 1e-5
    u = u / (rad_max + eps)
    v = v / (rad_max + eps)
    return flow_uv_to_colors(u, v, convert_to_bgr)
