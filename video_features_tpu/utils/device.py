"""Device resolution helpers shared by extractors."""
from __future__ import annotations

import jax


def jax_device(device: str) -> jax.Device:
    """Map a resolved config device string ('cpu'/'tpu') to a jax.Device.

    Tests run with a TPU plugin still registered, so 'cpu' must explicitly
    target the CPU backend rather than the default device.
    """
    platform = 'cpu' if str(device).lower() == 'cpu' else None
    if platform is None:
        platforms = {d.platform for d in jax.devices()}
        platform = next((p for p in platforms if p != 'cpu'), 'cpu')
    return jax.devices(platform)[0]
