"""Device resolution helpers shared by extractors."""
from __future__ import annotations

import os

import jax

MATMUL_PRECISIONS = ('default', 'high', 'highest',
                     'bfloat16', 'tensorfloat32', 'float32')


def _host_fingerprint() -> str:
    """Architecture + CPU-feature-flag hash identifying this host's
    executable compatibility. Same-arch hosts with different ISA extensions
    (AVX-512 vs not) must NOT share XLA:CPU AOT cache entries — the
    architecture name alone ('x86_64') cannot tell them apart."""
    import hashlib
    import platform as _platform
    flags = ''
    try:
        with open('/proc/cpuinfo') as f:
            for line in f:
                if line.startswith(('flags', 'Features')):
                    flags = line
                    break
    except OSError:
        flags = _platform.processor()
    h = hashlib.sha1(flags.encode()).hexdigest()[:8]
    return f'{_platform.machine()}-{h}'


def enable_compilation_cache(cache_dir, device: str = 'any') -> None:
    """Point jax's persistent compilation cache at ``cache_dir``.

    The fused extraction graphs take minutes to compile at ``highest``
    precision; the cache makes every process after the first (restarted or
    concurrent shared-filesystem workers — the reference's scale-out unit,
    reference README.md:70-84) skip straight to execution. Falsy
    ``cache_dir`` disables. Safe to call repeatedly; failures (read-only
    filesystem, backend without executable serialization) degrade to
    cache misses, never errors.

    ``device`` (the resolved config device — passed rather than asking
    jax, which would initialize backends before a CPU run pins its
    platform) scopes the directory: XLA:CPU AOT entries record the
    compiling machine's CPU features and can SIGILL when loaded on a
    different machine, so a shared dir must never serve entries across
    backends or heterogeneous hosts.
    """
    if not cache_dir:
        return
    try:
        # the ISA-fingerprint hazard only applies to XLA:CPU AOT entries;
        # accelerator executables don't depend on host CPU features, so any
        # non-CPU device keeps one shared subdir across hosts (full hit
        # rate). 'any' (unresolved device) gets the safe fingerprinted dir.
        sub = (f'{device}-{_host_fingerprint()}'
               if device in ('cpu', 'any') else device)
        path = os.path.join(os.path.expanduser(str(cache_dir)), sub)
        os.makedirs(path, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', path)
        # default threshold is 60s; our steady-state steps are seconds, so
        # cache everything that takes meaningful compile time
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    except Exception as e:  # pragma: no cover - depends on fs/backend
        print(f'WARNING: compilation cache unavailable ({e}); compiling cold')


def pin_cpu_platform() -> None:
    """Restrict jax to the CPU platform BEFORE backends initialize.

    Without this, jax initializes every registered plugin on first device
    access, and a remote-accelerator plugin (e.g. a TPU tunnel) can block a
    pure-CPU run for minutes dialing hardware it will never use. A shell
    ``JAX_PLATFORMS=cpu`` is not enough when a site hook pre-imports jax
    with its own value — the runtime config is the authoritative knob.
    No-op if backends are already up (the update then fails harmlessly).
    """
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass


def jax_device(device: str) -> jax.Device:
    """Map a resolved config device string ('cpu'/'tpu') to a jax.Device.

    Tests run with a TPU plugin still registered, so 'cpu' must explicitly
    target the CPU backend rather than the default device (and pin the
    platform first — see :func:`pin_cpu_platform`).
    """
    platform = 'cpu' if str(device).lower() == 'cpu' else None
    if platform == 'cpu':
        pin_cpu_platform()
    if platform is None:
        platforms = {d.platform for d in jax.devices()}
        platform = next((p for p in platforms if p != 'cpu'), 'cpu')
    return jax.devices(platform)[0]


def jax_devices_all(device: str) -> list:
    """All LOCAL devices of the platform :func:`jax_device` resolves to —
    the device set an in-process data-parallel mesh spans.

    Local, not global: under the multi-host runtime each host runs its own
    video shard (shared-nothing contract), so the in-graph mesh must stay on
    this host's addressable chips — a pod-global mesh would have every host
    deadlocking in collectives over different data.
    """
    first = jax_device(device)
    return [d for d in jax.local_devices() if d.platform == first.platform]
