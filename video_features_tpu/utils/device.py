"""Device resolution helpers shared by extractors."""
from __future__ import annotations

import os
import warnings

import jax

MATMUL_PRECISIONS = ('default', 'high', 'highest', 'mixed',
                     'bfloat16', 'tensorfloat32', 'float32')

# The ONE home of the shard_map version shim: jax >= 0.5 re-exports it at
# the top level, 0.4.x keeps it in experimental. Every shard_map consumer
# imports it from here so the next jax API move is a single edit.
try:
    from jax import shard_map  # noqa: F401
except ImportError:
    from jax.experimental.shard_map import shard_map  # noqa: F401


def enable_compilation_cache(cache_dir, device: str = 'any') -> None:
    """Point jax's persistent compilation cache at ``cache_dir``.

    The fused extraction graphs take minutes to compile at ``highest``
    precision; the cache makes every process after the first (restarted or
    concurrent shared-filesystem workers — the reference's scale-out unit,
    reference README.md:70-84) skip straight to execution. Falsy
    ``cache_dir`` disables. Safe to call repeatedly; failures (read-only
    filesystem, backend without executable serialization) degrade to
    cache misses, never errors.

    ``device`` (the resolved config device — passed rather than asking
    jax, which would initialize backends before a CPU run pins its
    platform) scopes the directory. XLA:CPU gets NO persistent cache:
    its AOT entries record the compiling machine's CPU feature list and
    the loader rejects (or worse, SIGILLs on) any mismatch — including
    same-host mismatches from feature-canonicalization differences
    (observed: '+prefer-no-scatter' recorded at compile, absent at load).
    CPU compiles are seconds, not minutes; the cache only pays on
    accelerators, whose serialized executables are host-independent.
    """
    try:
        current = jax.config.jax_compilation_cache_dir
    except AttributeError:  # pragma: no cover - very old jax
        current = None
    if not cache_dir or device in ('cpu', 'any'):
        if current:
            if device in ('cpu', 'any'):
                # The cache config is process-global: if an accelerator
                # extractor already enabled it, a later CPU extractor would
                # persist XLA:CPU AOT entries (host-ISA-fingerprinted) into
                # the host-SHARED accelerator dir — reject/SIGILL fodder
                # for other hosts. Clear it; correctness beats the
                # accelerator cache in mixed-device processes.
                warnings.warn(
                    'compilation cache disabled for this process '
                    f'(device={device!r} must not persist XLA:CPU '
                    f'entries into the shared dir {current})')
            else:
                # accelerator device with compilation_cache_dir=null: a
                # plain per-config opt-out, no CPU-entry hazard involved
                warnings.warn('compilation cache disabled per config '
                              f'(was {current})')
            try:
                jax.config.update('jax_compilation_cache_dir', None)
            except Exception:  # pragma: no cover
                # vft-lint: ok=swallowed-exception — best-effort unset on
                # ancient jax without the config key; compiles run cold
                pass
        return
    try:
        # accelerator executables don't depend on host CPU features, so
        # each non-CPU platform keeps one shared subdir across hosts
        # (full hit rate)
        path = os.path.join(os.path.expanduser(str(cache_dir)), device)
        if current and current != path:
            # the cache dir is process-global; a second extractor with a
            # different dir/device would silently redirect the first one's
            warnings.warn(
                f'compilation cache already at {current}; redirecting '
                f'to {path} (process-global — earlier extractors in '
                'this process now use the new dir)')
        os.makedirs(path, exist_ok=True)
        jax.config.update('jax_compilation_cache_dir', path)
        # default threshold is 60s; our steady-state steps are seconds, so
        # cache everything that takes meaningful compile time
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    except Exception as e:  # pragma: no cover - depends on fs/backend
        warnings.warn(f'compilation cache unavailable ({e}); '
                      'compiling cold')


def pin_cpu_platform() -> None:
    """Restrict jax to the CPU platform BEFORE backends initialize.

    Without this, jax initializes every registered plugin on first device
    access, and a remote-accelerator plugin (e.g. a TPU tunnel) can block a
    pure-CPU run for minutes dialing hardware it will never use. A shell
    ``JAX_PLATFORMS=cpu`` is not enough when a site hook pre-imports jax
    with its own value — the runtime config is the authoritative knob.
    No-op if backends are already up (the update then fails harmlessly).
    """
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        # vft-lint: ok=swallowed-exception — documented no-op when
        # backends are already up (the update fails harmlessly)
        pass


def jax_device(device: str) -> jax.Device:
    """Map a resolved config device string ('cpu'/'tpu') to a jax.Device.

    Tests run with a TPU plugin still registered, so 'cpu' must explicitly
    target the CPU backend rather than the default device (and pin the
    platform first — see :func:`pin_cpu_platform`).

    Always a LOCAL device: under the multi-process runtime
    (``multihost=true``) ``jax.devices()`` is the pod-GLOBAL list and its
    [0] is process 0's chip — committing a non-rank-0 extractor there makes
    every value fetch raise 'spans non-addressable devices' (caught by
    tests/test_multihost_integration.py).
    """
    platform = 'cpu' if str(device).lower() == 'cpu' else None
    if platform == 'cpu':
        pin_cpu_platform()
    if platform is None:
        platforms = {d.platform for d in jax.devices()}
        platform = next((p for p in platforms if p != 'cpu'), 'cpu')
    return jax.local_devices(backend=platform)[0]


def jax_devices_all(device: str) -> list:
    """All LOCAL devices of the platform :func:`jax_device` resolves to —
    the device set an in-process data-parallel mesh spans.

    Local, not global: under the multi-host runtime each host runs its own
    video shard (shared-nothing contract), so the in-graph mesh must stay on
    this host's addressable chips — a pod-global mesh would have every host
    deadlocking in collectives over different data.
    """
    first = jax_device(device)
    return [d for d in jax.local_devices() if d.platform == first.platform]
