"""CLI entry point: ``python -m video_features_tpu feature_type=X key=val ...``

Reference main.py:7-55 behavior: load per-feature YAML, merge dotlist CLI
(CLI wins), sanity-check, build the one extractor, shuffle the video list,
loop ``_extract`` per video with fault isolation.
"""
from __future__ import annotations

import sys
from typing import List, Optional

import yaml

from video_features_tpu.config import (
    form_list_from_user_input, load_config, parse_dotlist,
)
from video_features_tpu.registry import create_extractor


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == 'serve':
        # long-running warm-pool service (serve/): models stay resident,
        # requests arrive over a local socket and pack into shared batches
        from video_features_tpu.serve.server import serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == 'index':
        # offline feature-index surface (index/): fold the cache
        # manifest and run exact top-k queries without a resident server
        from video_features_tpu.index.cli import index_main
        return index_main(argv[1:])
    if argv and argv[0] == 'fleet':
        # multi-host front door (fleet/): consistent-hash routing over
        # N serve daemons — jax-free, so importing it never probes
        # devices in the router process
        from video_features_tpu.fleet.router import fleet_main
        return fleet_main(argv[1:])
    cli_args = parse_dotlist(argv)
    if 'feature_type' not in cli_args and 'features' not in cli_args:
        print('Usage: python -m video_features_tpu feature_type=<name> [key=value ...]\n'
              '       python -m video_features_tpu features=[f1,f2,...] [key=value ...]\n'
              '       python -m video_features_tpu serve [serve_port=N ...]\n'
              '       python -m video_features_tpu index --cache-dir DIR '
              '[--ingest] [--query vec.npy --family f]\n'
              '       python -m video_features_tpu fleet '
              'fleet_hosts=[h1:p1,h2:p2] [fleet_port=N ...]')
        return 2
    # single source of truth: multihost must come from the CLI because the
    # runtime must initialize before anything probes jax devices
    # (sanity_check inside load_config does) — a config-file value would be
    # seen too late and silently skip initialization
    multihost = bool(cli_args.get('multihost'))
    if multihost:
        from video_features_tpu.parallel.distributed import initialize
        # Pod environments autodetect everything (no extra keys needed);
        # manual clusters pass the coordinator triple per host:
        #   multihost=true coordinator_address=host0:1234 \
        #   num_processes=N process_id=<rank>
        initialize(cli_args.get('coordinator_address'),
                   cli_args.get('num_processes'),
                   cli_args.get('process_id'))
    if 'features' in cli_args:
        # fused multi-family worklist: decode each video once, branch the
        # shared frames into every family's transform + model
        return _fused_main(cli_args, multihost)
    args = load_config(cli_args['feature_type'], overrides=cli_args)
    if args.get('multihost') and not multihost:
        raise ValueError(
            'multihost must be passed on the command line (multihost=true), '
            'not via a config file: the distributed runtime must initialize '
            'before device probing')

    print(yaml.safe_dump(dict(args), sort_keys=False, default_flow_style=False))
    if args['on_extraction'] in ('save_numpy', 'save_pickle'):
        print(f'Saving features to {args["output_path"]}')
    print('Device:', args['device'])

    extractor = create_extractor(args)
    if extractor.blackbox is not None:
        # crash-dump black box (obs/blackbox.py): a fatal signal on a
        # CLI run dumps the recent spans/events/manifest before dying;
        # farm-worker deaths dump from the supervisor independently
        from video_features_tpu.obs.blackbox import install_signal_dump
        install_signal_dump(extractor.blackbox)

    # multihost: every host runs this same command; each takes a
    # deterministic interleaved shard of the list (no duplicate work across
    # healthy hosts) instead of the single-host collision-avoidance shuffle.
    video_paths = form_list_from_user_input(
        args.get('video_paths'), args.get('file_with_video_paths'),
        to_shuffle=not multihost)
    if multihost:
        from video_features_tpu.parallel import shard_worklist
        video_paths = shard_worklist(video_paths)
    print(f'The number of specified videos: {len(video_paths)}')

    # profile=true prints per-stage timing tables after each video;
    # profile_dir=<path> additionally captures a jax/XLA device trace;
    # trace_out=<path> records the host-side span timeline (Perfetto) and
    # manifest_out=<path> the per-run JSON manifest — both published by
    # finish_obs below even when a video failed (docs/observability.md).
    from video_features_tpu.utils.tracing import jax_profiler_trace
    try:
        with jax_profiler_trace(args.get('profile_dir')):
            if args.get('pack_across_videos'):
                # corpus mode: batch-major over the whole (per-host)
                # worklist — every device batch fills across video
                # boundaries, outputs and resume behavior are identical to
                # the per-video loop (parallel/packing.py)
                print(f'Packing device batches across {len(video_paths)} '
                      'videos')
                ahead = args.get('pack_decode_ahead')
                extractor.extract_packed(
                    video_paths,
                    decode_ahead=2 if ahead is None else int(ahead))
            else:
                for i, video_path in enumerate(video_paths):
                    print(f'[{i + 1}/{len(video_paths)}] {video_path}')
                    extractor._extract(video_path)
    finally:
        extractor.finish_obs()

    if multihost:
        # process 0 hosts the coordinator service: hold every process at a
        # final barrier so a host that drew short videos can't exit and tear
        # the coordinator down under hosts still extracting
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('extraction_done')
    return 0


def _fused_main(cli_args: dict, multihost: bool) -> int:
    """``features=[i3d,clip,...]`` worklists: one merged per-family config
    set (``config.load_fused_configs``), then families whose
    ``fused_decode_signature()`` values match share ONE decode pass per
    video (``parallel.packing.run_packed_fused``) while unfusable
    families run their own unchanged pass over the same worklist.
    Per-family outputs, cache keys, resume behavior, and fault isolation
    are identical to running each family sequentially — fusion only
    removes the repeated decode + content-hash work."""
    from video_features_tpu.config import load_fused_configs
    configs = load_fused_configs(cli_args['features'], overrides=cli_args)
    for fam_args in configs.values():
        if fam_args.get('multihost') and not multihost:
            raise ValueError(
                'multihost must be passed on the command line '
                '(multihost=true), not via a config file: the distributed '
                'runtime must initialize before device probing')

    print(f'Fused worklist ({len(configs)} families): '
          + ', '.join(configs))
    for fam, fam_args in configs.items():
        line = (f'  {fam}: device={fam_args["device"]} '
                f'on_extraction={fam_args["on_extraction"]}')
        if fam_args['on_extraction'] in ('save_numpy', 'save_pickle'):
            line += f' -> {fam_args["output_path"]}'
        print(line)

    exs = {fam: create_extractor(fam_args)
           for fam, fam_args in configs.items()}
    first = next(iter(exs.values()))
    if first.blackbox is not None:
        from video_features_tpu.obs.blackbox import install_signal_dump
        install_signal_dump(first.blackbox)

    # the worklist knobs are SHARED overrides (split_fused_overrides):
    # every family's config carries the same values, so read the first
    shared = next(iter(configs.values()))
    video_paths = form_list_from_user_input(
        shared.get('video_paths'), shared.get('file_with_video_paths'),
        to_shuffle=not multihost)
    if multihost:
        from video_features_tpu.parallel import shard_worklist
        video_paths = shard_worklist(video_paths)
    print(f'The number of specified videos: {len(video_paths)}')

    # group by decode signature: equal signatures branch off ONE shared
    # raw frame stream; a family with no signature (stack/audio families,
    # or an unspecced transform) can't, and keeps its own decode pass
    groups: dict = {}
    singles: List[str] = []
    for fam, ex in exs.items():
        sig = ex.fused_decode_signature()
        if sig is None:
            singles.append(fam)
        else:
            groups.setdefault(sig, {})[fam] = ex
    fused_groups = [g for g in groups.values() if len(g) > 1]
    singles.extend(fam for g in groups.values() if len(g) == 1
                   for fam in g)

    ahead = shared.get('pack_decode_ahead')
    decode_ahead = 2 if ahead is None else int(ahead)
    from video_features_tpu.utils.tracing import jax_profiler_trace
    try:
        with jax_profiler_trace(shared.get('profile_dir')):
            if fused_groups:
                from video_features_tpu.parallel.packing import (
                    run_packed_fused,
                )
            for group in fused_groups:
                print(f'Fusing decode for [{", ".join(group)}]: one '
                      f'pass over {len(video_paths)} videos')
                run_packed_fused(group, list(video_paths),
                                 decode_ahead=decode_ahead)
            for fam in singles:
                ex = exs[fam]
                print(f'[{fam}] cannot share a decode pass — running '
                      'its own')
                if getattr(ex, 'supports_packing', False):
                    ex.extract_packed(list(video_paths),
                                      decode_ahead=decode_ahead)
                else:
                    for i, video_path in enumerate(video_paths):
                        print(f'[{fam}] [{i + 1}/{len(video_paths)}] '
                              f'{video_path}')
                        ex._extract(video_path)
    finally:
        for ex in exs.values():
            ex.finish_obs()

    if multihost:
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices('extraction_done')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
