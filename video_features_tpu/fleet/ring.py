"""The consistent-hash ring behind fleet routing.

Classic virtual-node construction: every host hashes to ``replicas``
points on a 64-bit ring; a key routes to the first host point at or
after its own hash (wrapping). Properties the fleet relies on — and
the tests pin:

  * **determinism** — every router instance over the same host list
    computes the same assignment, with no coordination;
  * **minimal movement** — removing a host reassigns ONLY the keys it
    owned (~1/N of the space for N equal hosts); every other key keeps
    its backend, so its L1 cache and warm pools stay hot;
  * **stable failover order** — :meth:`hosts_for` walks the ring's
    successors, so "the next host" for a failed primary is the same
    host every router would pick, and retries concentrate a key's
    traffic on at most a couple of shards instead of spraying it.

Keys are strings (the video's content sha256 in practice); hosts are
opaque strings too (``host:port``). Hashing is sha256-derived rather
than ``hash()``: Python's string hash is salted per process, and a
ring that disagrees across processes would defeat the whole point.
"""
from __future__ import annotations

from bisect import bisect_right
from hashlib import sha256
from typing import Iterable, List, Sequence

DEFAULT_REPLICAS = 64


def _point(label: str) -> int:
    """A stable 64-bit ring coordinate for ``label``."""
    return int.from_bytes(sha256(label.encode('utf-8')).digest()[:8], 'big')


class HashRing:
    """An immutable consistent-hash ring over a static host list.

    Membership changes (a host drained, died, or was removed from
    ``fleet_hosts``) build a NEW ring — the structure is cheap (sorted
    list of ints) and immutability keeps the router's probe thread and
    request threads from ever seeing a half-updated ring.
    """

    def __init__(self, hosts: Sequence[str],
                 replicas: int = DEFAULT_REPLICAS) -> None:
        self.hosts: List[str] = list(dict.fromkeys(str(h) for h in hosts))
        self.replicas = int(replicas)
        points = []
        for host in self.hosts:
            for i in range(self.replicas):
                points.append((_point(f'{host}#{i}'), host))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [h for _, h in points]

    def __len__(self) -> int:
        return len(self.hosts)

    def without(self, host: str) -> 'HashRing':
        """The ring minus ``host`` (same replica count)."""
        return HashRing([h for h in self.hosts if h != host],
                        replicas=self.replicas)

    def host_for(self, key: str) -> str:
        """The key's owner (first host clockwise of the key's point)."""
        if not self.hosts:
            raise ValueError('empty hash ring')
        i = bisect_right(self._points, _point(str(key)))
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def hosts_for(self, key: str,
                  eligible: 'Iterable[str] | None' = None) -> List[str]:
        """Every distinct host in ring order starting at the key's
        owner — the router's failover sequence. ``eligible`` (when
        given) filters the walk to live hosts WITHOUT rebuilding the
        ring: a dead host is skipped, but the keys it owned all land on
        its ring successor (minimal movement), and every other key's
        owner is untouched."""
        if not self.hosts:
            return []
        allowed = None if eligible is None else set(eligible)
        start = bisect_right(self._points, _point(str(key)))
        out: List[str] = []
        seen = set()
        n = len(self._points)
        for off in range(n):
            host = self._owners[(start + off) % n]
            if host in seen:
                continue
            seen.add(host)
            if allowed is None or host in allowed:
                out.append(host)
            if len(seen) == len(self.hosts):
                break
        return out
