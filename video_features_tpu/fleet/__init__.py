"""Fleet-scale serving: the multi-host tier over the one-host daemon.

One :mod:`serve` daemon scales a host's chips; this package scales
hosts. Three pieces, each federating a seam the single-host tree
already exposes:

  * :mod:`fleet.ring` — the consistent-hash ring. Requests route by
    VIDEO CONTENT HASH (the same sha256 the content-addressed cache
    keys on), so each shard's feature cache and warm pools stay hot for
    the videos it owns, and removing a host moves only ~1/N of the key
    space (the ring property the rebalance test pins).
  * :mod:`fleet.router` — the front door: a stdlib-only router speaking
    both the loopback JSON-lines protocol and the ingress HTTP surface,
    with per-backend health probes, drain-aware membership, and
    bounded retry-with-backoff failover to the ring's next host on
    connect failure or shed (driven by the wire-1.4 structured error
    ``code``, never by message text).
  * :mod:`fleet.tier` / :mod:`fleet.artifacts` — the shared tiers: the
    feature cache promoted to local-L1 + shared-directory-L2 (a miss on
    host A that host B already extracted materializes byte-identically
    without decode), and the AOT executable store as the fleet's shared
    artifact tier (a freshly provisioned host pulls executables a peer
    compiled and serves its first request compile-free).

Everything here is deliberately importable without jax: the router and
both tiers move bytes and JSON; what the bytes mean lives in the
subsystems they federate.
"""
from video_features_tpu.fleet.ring import HashRing

__all__ = ['HashRing']
