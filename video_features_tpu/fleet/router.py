"""The fleet front door: consistent-hash routing over N serve daemons.

One router process speaks BOTH surfaces the single-host daemon does:

  * the loopback JSON-lines protocol (``serve/protocol.py``) — so a
    ``ServeClient`` pointed at the router is indistinguishable from one
    pointed at a daemon (submit/status/trace/search/metrics/ping), and
    the CLI/tests drive the fleet with zero new client code;
  * the ingress HTTP surface (``ingress/http.py`` transport +
    ``ingress/auth.py`` API keys + ``ingress/quota.py`` tenant gates)
    when ``fleet_http_port`` is set — ``POST /v1/extract``,
    ``POST /v1/search``, ``GET /v1/requests/<id>``, ``GET /v1/metrics``,
    and an unauthenticated ``GET /healthz`` carrying the per-backend
    health table.

Routing: requests key on the first video's CONTENT hash (the same
sha256 the content-addressed cache keys on — ``cache/key.hash_file``),
so every video's repeat traffic lands on the shard whose L1 cache and
warm pools already hold it. Vector searches key on the family.

Failover (the wire-1.4 contract): a backend failure is classified by
its structured error ``code`` — ``shed`` / ``connect_refused`` /
``deadline`` walk to the hash ring's NEXT host with bounded
exponential backoff (at most ``fleet_max_attempts`` hosts); everything
else (``invalid``, ``unsupported``, ``not_found``, ``internal``)
propagates to the caller, because a request the whole fleet would
reject identically must not be retried N times. Message text never
drives the decision.

Membership: ``fleet_hosts`` is static config; LIVENESS is probed — a
background thread pings every backend each ``fleet_probe_interval_s``,
and the ping response's ``draining`` flag (wire 1.1+) removes a
draining host from the eligible set before its listener closes
(drain-aware membership). A connect failure on the REQUEST path marks
the backend unhealthy immediately — the next submit skips it without
waiting for the probe cycle. Unhealthy hosts stay ON the ring
(eligibility is a filter, not a rebuild), so when one returns, exactly
its own keys come home.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from video_features_tpu.fleet.ring import DEFAULT_REPLICAS, HashRing
from video_features_tpu.serve import protocol
from video_features_tpu.serve.client import ServeClient, ServeError

# request_id → backend retention for status/trace routing; same bound
# as the daemons' own request history
ROUTE_HISTORY = 4096


def _log_fleet_error(what: str) -> None:
    """Router-path failures degrade to failover or a structured error,
    never to a dropped request — but silently eating them would hide a
    dead backend forever. Same reporting seam as cache/aot."""
    import logging

    from video_features_tpu.obs.events import event
    event(logging.WARNING, f'fleet router {what} failed (continuing)',
          subsystem='fleet', exc_info=True)


class Backend:
    """One configured backend host and its probed liveness."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        host, _, port = addr.rpartition(':')
        self.host = host or '127.0.0.1'
        self.port = int(port)
        self.healthy = False
        self.draining = False
        self.last_probe_t = 0.0
        self.last_error: Optional[str] = None
        self.consecutive_failures = 0

    def snapshot(self) -> Dict[str, Any]:
        return {'healthy': self.healthy, 'draining': self.draining,
                'last_probe_t': self.last_probe_t,
                'last_error': self.last_error,
                'consecutive_failures': self.consecutive_failures}


class FleetRouter:
    """Content-hash router over a static backend list."""

    # failover backoff between ring hosts: same shape as ServeClient's
    # connect backoff — short, doubling, jitter-free (the per-host
    # connect path already jitters)
    _BACKOFF_CAP_S = 0.5

    def __init__(self, hosts: List[str], host: str = '127.0.0.1',
                 port: int = 0,
                 http_host: str = '127.0.0.1',
                 http_port: Optional[int] = None,
                 auth_file: Optional[str] = None,
                 auth: Optional[Any] = None,
                 probe_interval_s: float = 2.0,
                 max_attempts: int = 3,
                 backoff_base_s: float = 0.05,
                 connect_timeout_s: float = 2.0,
                 ring_replicas: int = DEFAULT_REPLICAS,
                 max_connections: int = 64) -> None:
        addrs = []
        for h in hosts:
            addr = str(h)
            if ':' not in addr:
                addr = f'127.0.0.1:{addr}'   # bare port = loopback sim
            addrs.append(addr)
        if not addrs:
            raise ValueError('fleet_hosts must name at least one backend')
        self.ring = HashRing(addrs, replicas=ring_replicas)
        self._backends = {a: Backend(a) for a in self.ring.hosts}
        self.host, self._port_req = host, int(port)
        self.probe_interval_s = float(probe_interval_s)
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = float(backoff_base_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._lock = threading.Lock()
        self._draining = False
        self._started_at = time.monotonic()
        # request_id → backend addr (status/trace routing), bounded
        self._routes: Dict[str, str] = {}
        self._route_order: 'deque[str]' = deque()
        # counters (under _lock)
        self._routed: Dict[str, int] = {a: 0 for a in self.ring.hosts}
        self._failovers = 0
        self._rejected = 0
        self._sock = None
        self._accept_thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # optional HTTP front door (reuses the ingress transport/auth)
        self.http = None
        self._http_auth = auth
        self._http_host, self._http_port = http_host, http_port
        self._http_auth_file = auth_file
        self._quota = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._sock is not None, 'router not started'
        return self._sock.getsockname()[1]

    def start(self) -> 'FleetRouter':
        import socket
        # one synchronous probe sweep BEFORE accepting traffic, so the
        # first request sees real membership, not all-unhealthy
        self.probe()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._port_req))
        self._sock.listen(64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name='fleet-accept', daemon=True)
        self._accept_thread.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name='fleet-probe', daemon=True)
        self._probe_thread.start()
        if self._http_port is not None:
            from video_features_tpu.ingress.auth import ApiKeyAuth
            from video_features_tpu.ingress.http import HttpServer
            from video_features_tpu.ingress.quota import QuotaManager
            if self._http_auth is None:
                if not self._http_auth_file:
                    raise ValueError('the fleet HTTP front door requires '
                                     'an API-key file (fleet_auth_file)')
                self._http_auth = ApiKeyAuth.from_file(self._http_auth_file)
            self._quota = QuotaManager()
            self.http = HttpServer(self._handle_http,
                                   host=self._http_host,
                                   port=int(self._http_port)).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._draining = True
        if self.http is not None:
            self.http.begin_drain()
            self.http.finish_drain(grace_s=1.0)
        if self._sock is not None:
            import socket
            try:
                # shutdown BEFORE close: a bare close leaves the
                # listener half-alive while the accept thread is blocked
                # on it, and one more connection would sneak through
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    # -- membership ----------------------------------------------------------

    def _probe_call(self, b: Backend) -> Dict[str, Any]:
        """One raw ping with a HARD read deadline — ServeClient leaves
        reads unbounded (extraction can take a while), but a wedged
        backend that accepts and never answers must cost the probe
        thread half a second, not its liveness."""
        import socket
        timeout = min(0.5, self.connect_timeout_s)
        with socket.create_connection((b.host, b.port),
                                      timeout=timeout) as conn:
            conn.settimeout(timeout)
            conn.sendall(protocol.encode({'cmd': protocol.CMD_PING,
                                          'v': protocol.VERSION}))
            with conn.makefile('rb') as rfile:
                line = rfile.readline()
        if not line:
            raise ConnectionError('backend closed the probe connection')
        return protocol.decode(line)

    def probe(self) -> Dict[str, Dict[str, Any]]:
        """One synchronous health sweep; returns the per-backend table.
        ``ping`` (wire 1.1+) answers ``draining`` — a draining host is
        alive but leaves the eligible set."""
        for b in self._backends.values():
            try:
                resp = self._probe_call(b)
                with self._lock:
                    b.healthy = bool(resp.get('ok'))
                    b.draining = bool(resp.get('draining'))
                    b.last_error = None
                    b.consecutive_failures = 0
            except (ServeError, OSError, ValueError) as e:
                with self._lock:
                    b.healthy = False
                    b.last_error = f'{type(e).__name__}: {e}'
                    b.consecutive_failures += 1
            finally:
                with self._lock:
                    b.last_probe_t = time.time()
        with self._lock:
            return {a: b.snapshot() for a, b in self._backends.items()}

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe()
            except Exception:
                _log_fleet_error('probe sweep')

    def eligible(self) -> List[str]:
        """Backends the ring may route to: healthy and not draining."""
        with self._lock:
            return [a for a, b in self._backends.items()
                    if b.healthy and not b.draining]

    # -- routing core --------------------------------------------------------

    @staticmethod
    def route_key(msg: Dict[str, Any]) -> str:
        """The consistent-hash key for one request: the first video's
        CONTENT hash (cache-key identity — repeat traffic for a video
        lands where its features are cached), the path itself when the
        file isn't readable yet (the backend will answer the error),
        or the family for vector searches."""
        paths = msg.get('video_paths') or []
        video = msg.get('video_path')
        if video is not None and not paths:
            paths = [video]
        if paths:
            from video_features_tpu.cache.key import hash_file
            try:
                return hash_file(str(paths[0]))
            except OSError:
                return str(paths[0])
        return f"family:{msg.get('family')}"

    def _remember_route(self, request_id: str, addr: str) -> None:
        with self._lock:
            self._routes[request_id] = addr
            self._route_order.append(request_id)
            while len(self._route_order) > ROUTE_HISTORY:
                self._routes.pop(self._route_order.popleft(), None)

    def _backend_call(self, addr: str,
                      msg: Dict[str, Any]) -> Dict[str, Any]:
        b = self._backends[addr]
        client = ServeClient(b.port, host=b.host,
                             connect_timeout_s=self.connect_timeout_s)
        return client._call(dict(msg))

    def _route(self, key: str, msg: Dict[str, Any],
               on_success: Optional[Callable[[Dict[str, Any], str],
                                             None]] = None,
               ) -> Dict[str, Any]:
        """Walk the ring's failover order for ``key``, forwarding
        ``msg``; classify each failure by its structured code and
        either walk on (shed / connect_refused / deadline) or
        propagate. Returns the successful backend response, or the
        LAST failure as a structured error."""
        hosts = self.ring.hosts_for(key, eligible=self.eligible())
        if not hosts:
            with self._lock:
                self._rejected += 1
            return protocol.error('no eligible fleet backend '
                                  '(all unhealthy or draining)',
                                  code=protocol.ERR_SHED)
        delay = self.backoff_base_s
        last: Optional[ServeError] = None
        for i, addr in enumerate(hosts[:self.max_attempts]):
            if i > 0:
                with self._lock:
                    self._failovers += 1
                time.sleep(delay)
                delay = min(delay * 2, self._BACKOFF_CAP_S)
            try:
                resp = self._backend_call(addr, msg)
            except ServeError as e:
                last = e
                if e.code == protocol.ERR_CONNECT_REFUSED:
                    # fast member removal: don't wait for the probe
                    with self._lock:
                        b = self._backends[addr]
                        b.healthy = False
                        b.last_error = str(e)
                        b.consecutive_failures += 1
                if e.retryable:
                    continue
                break
            except (OSError, ValueError) as e:
                # transport surprise outside the classified set (reset
                # mid-read, undecodable response): treat as shed —
                # another host may serve it — but remember the text
                last = ServeError(f'{type(e).__name__}: {e}',
                                  code=protocol.ERR_SHED)
                continue
            with self._lock:
                self._routed[addr] = self._routed.get(addr, 0) + 1
            if on_success is not None:
                on_success(resp, addr)
            return resp
        with self._lock:
            self._rejected += 1
        assert last is not None
        return protocol.error(str(last),
                              code=last.code or protocol.ERR_INTERNAL,
                              **{k: v for k, v in last.extra.items()
                                 if k not in ('ok', 'error', 'code')})

    # -- command handlers ----------------------------------------------------

    def submit(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if self._draining:
                self._rejected += 1
                return protocol.error('draining',
                                      code=protocol.ERR_SHED)

        def _remember(resp: Dict[str, Any], addr: str) -> None:
            rid = resp.get('request_id')
            if rid:
                self._remember_route(rid, addr)
            # fused children route with the umbrella
            for child in (resp.get('requests') or {}).values():
                self._remember_route(child, addr)
            resp['backend'] = addr

        return self._route(self.route_key(msg), msg,
                           on_success=_remember)

    def request_scoped(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """status/trace: route by the remembered request_id → backend
        binding (content hash is not recoverable from an id)."""
        rid = msg.get('request_id')
        with self._lock:
            addr = self._routes.get(rid)
        if addr is None:
            return protocol.error(f'unknown request_id {rid!r}',
                                  code=protocol.ERR_NOT_FOUND)
        try:
            return self._backend_call(addr, msg)
        except ServeError as e:
            return protocol.error(str(e),
                                  code=e.code or protocol.ERR_INTERNAL)

    def search(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return self._route(self.route_key(msg), msg)

    def forward_any(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Commands with no content affinity (index_status): any
        eligible backend, ring-ordered on a constant key for
        stability."""
        return self._route('fleet:any', msg)

    def metrics(self) -> Dict[str, Any]:
        """The fleet metrics document: router counters + the
        per-backend table (health, queue depth, cache hit rate — the
        ``tools/fleet_status.py`` surface). Backend metrics are
        fetched live from healthy hosts; a host that fails the fetch
        degrades to its probe row."""
        with self._lock:
            backends = {a: b.snapshot()
                        for a, b in self._backends.items()}
            doc: Dict[str, Any] = {
                'uptime_s': round(time.monotonic() - self._started_at, 3),
                'draining': self._draining,
                'hosts': list(self.ring.hosts),
                'routed': dict(self._routed),
                'failovers': self._failovers,
                'rejected': self._rejected,
            }
        for addr, row in backends.items():
            if not row['healthy']:
                continue
            try:
                m = self._backend_call(addr,
                                       {'cmd': protocol.CMD_METRICS})
                bm = m.get('metrics') or {}
                row['queue_depth'] = (bm.get('queue') or {}).get('depth')
                row['cache_hit_rate'] = \
                    (bm.get('cache') or {}).get('hit_rate')
                row['builds_compiled'] = \
                    (bm.get('warm_pool') or {}).get('builds_compiled')
                row['builds_loaded'] = \
                    (bm.get('warm_pool') or {}).get('builds_loaded')
            except (ServeError, OSError, ValueError):
                _log_fleet_error(f'metrics fetch from {addr}')
        doc['eligible'] = [a for a, r in backends.items()
                           if r['healthy'] and not r['draining']]
        doc['backends'] = backends
        return {'fleet': doc}

    # -- loopback listener ---------------------------------------------------

    def _dispatch(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        rejection = protocol.check_version(msg)
        if rejection is not None:
            return rejection
        cmd = msg.get('cmd')
        if cmd == protocol.CMD_PING:
            with self._lock:
                draining = self._draining
            return protocol.ok(draining=draining, v=protocol.VERSION,
                               fleet_hosts=len(self.ring))
        if cmd == protocol.CMD_SUBMIT:
            return self.submit(msg)
        if cmd in (protocol.CMD_STATUS, protocol.CMD_TRACE):
            return self.request_scoped(msg)
        if cmd == protocol.CMD_SEARCH:
            return self.search(msg)
        if cmd == protocol.CMD_INDEX_STATUS:
            return self.forward_any(msg)
        if cmd == protocol.CMD_METRICS:
            return protocol.ok(metrics=self.metrics())
        if cmd == protocol.CMD_METRICS_PROM:
            # per-host exposition belongs to each backend's own scrape
            # target; aggregating text format here would double-count
            return protocol.error(
                'metrics_prom is per-backend — scrape the daemons',
                code=protocol.ERR_UNSUPPORTED)
        if cmd == protocol.CMD_DRAIN:
            with self._lock:
                self._draining = True
            return protocol.ok(draining=True)
        return protocol.error(
            f'unknown cmd {cmd!r}; known: {", ".join(protocol.COMMANDS)}',
            code=protocol.ERR_INVALID)

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return                     # socket closed: stopping
            threading.Thread(target=self._handle_conn, args=(conn,),
                             name='fleet-conn', daemon=True).start()

    def _handle_conn(self, conn) -> None:
        try:
            with conn:
                rfile = conn.makefile('rb')
                wfile = conn.makefile('wb')
                for line in rfile:
                    try:
                        msg = protocol.decode(line)
                        resp = self._dispatch(msg)
                    except Exception as e:
                        resp = protocol.error(f'{type(e).__name__}: {e}',
                                              code=protocol.ERR_INTERNAL)
                    try:
                        wfile.write(protocol.encode(resp))
                        wfile.flush()
                    except (OSError, ValueError):
                        return             # client went away mid-reply
        except OSError:
            pass                           # torn connection: next client

    # -- HTTP front door -----------------------------------------------------

    # structured code → HTTP status for propagated backend errors
    _CODE_STATUS: Dict[str, int] = {}

    @classmethod
    def _code_to_status(cls, code: Optional[str]) -> int:
        from video_features_tpu.ingress import http as h
        if not cls._CODE_STATUS:
            cls._CODE_STATUS.update({
                protocol.ERR_SHED: h.SERVICE_UNAVAILABLE,
                protocol.ERR_CONNECT_REFUSED: h.SERVICE_UNAVAILABLE,
                protocol.ERR_DEADLINE: h.SERVICE_UNAVAILABLE,
                protocol.ERR_INVALID: h.BAD_REQUEST,
                protocol.ERR_UNSUPPORTED: h.BAD_REQUEST,
                protocol.ERR_NOT_FOUND: h.NOT_FOUND,
                protocol.ERR_INTERNAL: h.INTERNAL_ERROR,
            })
        return cls._CODE_STATUS.get(code or '', h.INTERNAL_ERROR)

    def _handle_http(self, req, resp, conn) -> None:
        from video_features_tpu.ingress import http as h
        try:
            if req.method == 'GET' and req.path == '/healthz':
                # NO auth: load balancers probe this
                with self._lock:
                    table = {a: {'healthy': b.healthy,
                                 'draining': b.draining}
                             for a, b in self._backends.items()}
                    draining = self._draining
                resp.send_json(h.OK, {'ok': True, 'draining': draining,
                                      'fleet': True, 'backends': table})
                return
            tenant = self._http_auth.authenticate(req.headers)
            if tenant is None:
                resp.send_json(h.UNAUTHORIZED, {
                    'ok': False, 'error': 'unauthorized',
                    'message': 'missing or unknown API key '
                               '(Authorization: Bearer <key>)'})
                return
            if req.method == 'GET' and req.path == '/v1/metrics':
                resp.send_json(h.OK, {'ok': True,
                                      'metrics': self.metrics()})
                return
            if req.method == 'GET' \
                    and req.path.startswith('/v1/requests/'):
                rid = req.path[len('/v1/requests/'):].strip('/')
                out = self.request_scoped(
                    {'cmd': protocol.CMD_STATUS, 'request_id': rid})
                status = h.OK if out.get('ok') \
                    else self._code_to_status(out.get('code'))
                resp.send_json(status, out)
                return
            if req.method == 'POST' \
                    and req.path in ('/v1/extract', '/v1/search'):
                body = req.json_body(16 * (1 << 20))
                acquired, reason = self._quota.acquire(tenant)
                if not acquired:
                    resp.send_json(
                        h.TOO_MANY_REQUESTS,
                        {'ok': False, 'error': reason,
                         'tenant': tenant.name})
                    return
                try:
                    if req.path == '/v1/extract':
                        msg = {'cmd': protocol.CMD_SUBMIT}
                        for k in protocol.SUBMIT_FIELDS:
                            if k in body:
                                msg[k] = body[k]
                        tp = req.headers.get('traceparent')
                        if tp and 'traceparent' not in msg:
                            msg['traceparent'] = tp
                        out = self.submit(msg)
                    else:
                        msg = dict(body)
                        msg['cmd'] = protocol.CMD_SEARCH
                        out = self.search(msg)
                finally:
                    # the router holds the concurrency unit only for
                    # the forward itself: completion lives on the
                    # backend, and its own ingress (when enabled)
                    # owns per-request lifetime quota
                    self._quota.release(tenant.name)
                status = h.OK if out.get('ok') \
                    else self._code_to_status(out.get('code'))
                resp.send_json(status, out)
                return
            raise h.HttpError(h.NOT_FOUND, 'not_found',
                              f'no fleet route {req.method} {req.path}')
        except h.HttpError as e:
            resp.send_json(e.status, e.body())


def fleet_main(argv: List[str]) -> int:
    """``python -m video_features_tpu fleet`` entry point."""
    import os
    import signal

    from video_features_tpu.config import parse_dotlist, split_fleet_config
    cli = parse_dotlist(argv)
    fleet_cfg, extra = split_fleet_config(cli)
    if extra:
        raise ValueError(
            f'unknown fleet keys: {sorted(extra)} — the router takes '
            f'only fleet_* knobs (backends own extraction config)')
    hosts = fleet_cfg['fleet_hosts']
    if not hosts:
        raise ValueError('fleet_hosts is required, e.g. '
                         'fleet_hosts=[127.0.0.1:9301,127.0.0.1:9302]')
    router = FleetRouter(
        hosts,
        host=fleet_cfg['fleet_host'],
        port=fleet_cfg['fleet_port'],
        http_host=fleet_cfg['fleet_http_host'],
        http_port=fleet_cfg['fleet_http_port'],
        auth_file=fleet_cfg['fleet_auth_file'],
        probe_interval_s=fleet_cfg['fleet_probe_interval_s'],
        max_attempts=fleet_cfg['fleet_max_attempts'],
        backoff_base_s=fleet_cfg['fleet_backoff_base_s'],
        connect_timeout_s=fleet_cfg['fleet_connect_timeout_s'],
        ring_replicas=fleet_cfg['fleet_ring_replicas'],
    ).start()
    done = threading.Event()

    def _graceful(signum, frame):
        router.stop()
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    # machine-greppable endpoint line (tests and tooling scrape it,
    # same contract as the serve daemon's startup line)
    # vft-lint: ok=stdout-purity — documented startup line (fleet)
    print(f'fleet router on {router.host}:{router.port} '
          f'(pid {os.getpid()}; backends={",".join(router.ring.hosts)}, '
          f'eligible={len(router.eligible())})', flush=True)
    if router.http is not None:
        # vft-lint: ok=stdout-purity — documented startup line (fleet)
        print(f'fleet ingress on {router.http.host}:{router.http.port}',
              flush=True)
    done.wait()
    # vft-lint: ok=stdout-purity — shutdown line of the same contract
    print('fleet: stopped, exiting', flush=True)
    return 0
