"""The fleet front door: consistent-hash routing over N serve daemons.

One router process speaks BOTH surfaces the single-host daemon does:

  * the loopback JSON-lines protocol (``serve/protocol.py``) — so a
    ``ServeClient`` pointed at the router is indistinguishable from one
    pointed at a daemon (submit/status/trace/search/metrics/ping), and
    the CLI/tests drive the fleet with zero new client code;
  * the ingress HTTP surface (``ingress/http.py`` transport +
    ``ingress/auth.py`` API keys + ``ingress/quota.py`` tenant gates)
    when ``fleet_http_port`` is set — ``POST /v1/extract``,
    ``POST /v1/search``, ``GET /v1/requests/<id>``,
    ``GET /v1/requests/<id>/trace`` (cross-host assembled trace),
    ``GET /v1/metrics``, ``GET /metrics`` (fleet-aggregated Prometheus
    text), and an unauthenticated ``GET /healthz`` carrying the
    per-backend health table.

Routing: requests key on the first video's CONTENT hash (the same
sha256 the content-addressed cache keys on — ``cache/key.hash_file``),
so every video's repeat traffic lands on the shard whose L1 cache and
warm pools already hold it. Vector searches key on the family.

Failover (the wire-1.4 contract): a backend failure is classified by
its structured error ``code`` — ``shed`` / ``connect_refused`` /
``deadline`` walk to the hash ring's NEXT host with bounded
exponential backoff (at most ``fleet_max_attempts`` hosts); everything
else (``invalid``, ``unsupported``, ``not_found``, ``internal``)
propagates to the caller, because a request the whole fleet would
reject identically must not be retried N times. Message text never
drives the decision.

Membership: ``fleet_hosts`` is static config; LIVENESS is probed — a
background thread pings every backend each ``fleet_probe_interval_s``,
and the ping response's ``draining`` flag (wire 1.1+) removes a
draining host from the eligible set before its listener closes
(drain-aware membership). A connect failure on the REQUEST path marks
the backend unhealthy immediately — the next submit skips it without
waiting for the probe cycle. Unhealthy hosts stay ON the ring
(eligibility is a filter, not a rebuild), so when one returns, exactly
its own keys come home.

Observability (vft-scope): the router is the one hop every production
request crosses, so it records its own ``route`` / ``backend_call`` /
``failover`` / ``probe`` spans on a ``SpanRecorder``, mints or adopts
a W3C traceparent per submit and forwards it on every loopback hop —
one trace_id spans the whole fleet. The ``trace`` command (and
``GET /v1/requests/<id>/trace``) scatter-gathers: every backend the
request ATTEMPTED is asked for its spans (failover history included),
each event is stamped ``host=``, and the merge is ts-sorted under the
one trace_id. Per-host clocks are not aligned — the ts-sort is a
presentation order; the ``host`` attr is the ground truth for "where
did this span run". ``metrics_prom`` / ``GET /metrics`` aggregate
every backend's exposition (``fleet/aggregate.py``: host-relabel +
merge) with the router's own ``vft_fleet_*`` families and the always-on
fleet SLO burn-rate gauges (``obs/slo.py`` over the router's
routed-request families) — one scrape target for the whole fleet.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from video_features_tpu.fleet import aggregate
from video_features_tpu.fleet.ring import DEFAULT_REPLICAS, HashRing
from video_features_tpu.obs.context import TraceContext, accept_traceparent
from video_features_tpu.obs.metrics import MetricsRegistry
from video_features_tpu.obs.slo import SloEvaluator
from video_features_tpu.obs.spans import CLOCK, SpanRecorder
from video_features_tpu.serve import protocol
from video_features_tpu.serve.client import ServeClient, ServeError

# request_id → backend retention for status/trace routing; same bound
# as the daemons' own request history
ROUTE_HISTORY = 4096

# the router's span ring: routing spans are tiny (4 per routed request
# worst-case) so a fraction of the daemons' 200K default covers hours
ROUTER_TRACE_CAPACITY = 50_000


def _log_fleet_error(what: str) -> None:
    """Router-path failures degrade to failover or a structured error,
    never to a dropped request — but silently eating them would hide a
    dead backend forever. Same reporting seam as cache/aot."""
    import logging

    from video_features_tpu.obs.events import event
    event(logging.WARNING, f'fleet router {what} failed (continuing)',
          subsystem='fleet', exc_info=True)


class Backend:
    """One configured backend host and its probed liveness."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        host, _, port = addr.rpartition(':')
        self.host = host or '127.0.0.1'
        self.port = int(port)
        self.healthy = False
        self.draining = False
        self.last_probe_t = 0.0
        self.last_error: Optional[str] = None
        self.consecutive_failures = 0

    def snapshot(self) -> Dict[str, Any]:
        # probe_age_s makes freshness explicit: `healthy` alone can't
        # distinguish a live backend from one whose last GOOD probe is
        # a probe-loop stall ago (None = never probed)
        age = (round(time.time() - self.last_probe_t, 3)
               if self.last_probe_t else None)
        return {'healthy': self.healthy, 'draining': self.draining,
                'last_probe_t': self.last_probe_t,
                'probe_age_s': age,
                'last_error': self.last_error,
                'consecutive_failures': self.consecutive_failures}


class FleetRouter:
    """Content-hash router over a static backend list."""

    # failover backoff between ring hosts: same shape as ServeClient's
    # connect backoff — short, doubling, jitter-free (the per-host
    # connect path already jitters)
    _BACKOFF_CAP_S = 0.5

    def __init__(self, hosts: List[str], host: str = '127.0.0.1',
                 port: int = 0,
                 http_host: str = '127.0.0.1',
                 http_port: Optional[int] = None,
                 auth_file: Optional[str] = None,
                 auth: Optional[Any] = None,
                 probe_interval_s: float = 2.0,
                 max_attempts: int = 3,
                 backoff_base_s: float = 0.05,
                 connect_timeout_s: float = 2.0,
                 ring_replicas: int = DEFAULT_REPLICAS,
                 max_connections: int = 64,
                 slo_latency_p99_s: float = 30.0,
                 slo_availability: float = 0.999) -> None:
        addrs = []
        for h in hosts:
            addr = str(h)
            if ':' not in addr:
                addr = f'127.0.0.1:{addr}'   # bare port = loopback sim
            addrs.append(addr)
        if not addrs:
            raise ValueError('fleet_hosts must name at least one backend')
        self.ring = HashRing(addrs, replicas=ring_replicas)
        self._backends = {a: Backend(a) for a in self.ring.hosts}
        self.host, self._port_req = host, int(port)
        self.probe_interval_s = float(probe_interval_s)
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base_s = float(backoff_base_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._lock = threading.Lock()
        self._draining = False
        self._started_at = time.monotonic()
        # request_id → (owner addr, trace_id, attempted addrs) — the
        # owner routes status; the full attempt history (failovers
        # included) routes the scatter-gather trace assembly
        self._routes: Dict[str, Tuple[str, Optional[str],
                                      Tuple[str, ...]]] = {}
        self._route_order: 'deque[str]' = deque()
        # counters (under _lock)
        self._routed: Dict[str, int] = {a: 0 for a in self.ring.hosts}
        self._failovers = 0
        self._rejected = 0
        # vft-scope: the router's own observability plane — routing
        # spans, vft_fleet_* families, and the always-on fleet SLO
        # (its /metrics is the fleet's one scrape target, so the
        # vft_slo_* gauges must always render)
        self.recorder = SpanRecorder(capacity=ROUTER_TRACE_CAPACITY)
        self.registry = MetricsRegistry()
        self._latency_hist = self.registry.histogram(
            'vft_fleet_request_latency_seconds',
            'router-observed latency of routed requests (failover '
            'walk included)')
        self._req_completed = self.registry.counter(
            'vft_fleet_requests_total', 'routed requests by outcome',
            labels={'outcome': 'completed'})
        self._req_failed = self.registry.counter(
            'vft_fleet_requests_total', 'routed requests by outcome',
            labels={'outcome': 'failed'})
        self.slo = SloEvaluator(
            self.registry,
            latency_p99_s=slo_latency_p99_s,
            availability=slo_availability,
            latency_family='vft_fleet_request_latency_seconds',
            outcome_family='vft_fleet_requests_total')
        self._sock = None
        self._accept_thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # optional HTTP front door (reuses the ingress transport/auth)
        self.http = None
        self._http_auth = auth
        self._http_host, self._http_port = http_host, http_port
        self._http_auth_file = auth_file
        self._quota = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._sock is not None, 'router not started'
        return self._sock.getsockname()[1]

    def start(self) -> 'FleetRouter':
        import socket
        # one synchronous probe sweep BEFORE accepting traffic, so the
        # first request sees real membership, not all-unhealthy
        self.probe()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._port_req))
        self._sock.listen(64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name='fleet-accept', daemon=True)
        self._accept_thread.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name='fleet-probe', daemon=True)
        self._probe_thread.start()
        if self._http_port is not None:
            from video_features_tpu.ingress.auth import ApiKeyAuth
            from video_features_tpu.ingress.http import HttpServer
            from video_features_tpu.ingress.quota import QuotaManager
            if self._http_auth is None:
                if not self._http_auth_file:
                    raise ValueError('the fleet HTTP front door requires '
                                     'an API-key file (fleet_auth_file)')
                self._http_auth = ApiKeyAuth.from_file(self._http_auth_file)
            self._quota = QuotaManager()
            self.http = HttpServer(self._handle_http,
                                   host=self._http_host,
                                   port=int(self._http_port)).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            self._draining = True
        if self.http is not None:
            self.http.begin_drain()
            self.http.finish_drain(grace_s=1.0)
        if self._sock is not None:
            import socket
            try:
                # shutdown BEFORE close: a bare close leaves the
                # listener half-alive while the accept thread is blocked
                # on it, and one more connection would sneak through
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    # -- membership ----------------------------------------------------------

    def _probe_call(self, b: Backend) -> Dict[str, Any]:
        """One raw ping with a HARD read deadline — ServeClient leaves
        reads unbounded (extraction can take a while), but a wedged
        backend that accepts and never answers must cost the probe
        thread half a second, not its liveness."""
        import socket
        timeout = min(0.5, self.connect_timeout_s)
        with socket.create_connection((b.host, b.port),
                                      timeout=timeout) as conn:
            conn.settimeout(timeout)
            conn.sendall(protocol.encode({'cmd': protocol.CMD_PING,
                                          'v': protocol.VERSION}))
            with conn.makefile('rb') as rfile:
                line = rfile.readline()
        if not line:
            raise ConnectionError('backend closed the probe connection')
        return protocol.decode(line)

    def probe(self) -> Dict[str, Dict[str, Any]]:
        """One synchronous health sweep; returns the per-backend table.
        ``ping`` (wire 1.1+) answers ``draining`` — a draining host is
        alive but leaves the eligible set."""
        for b in self._backends.values():
            t0 = CLOCK()
            try:
                resp = self._probe_call(b)
                with self._lock:
                    b.healthy = bool(resp.get('ok'))
                    b.draining = bool(resp.get('draining'))
                    b.last_error = None
                    b.consecutive_failures = 0
            except (ServeError, OSError, ValueError) as e:
                with self._lock:
                    b.healthy = False
                    b.last_error = f'{type(e).__name__}: {e}'
                    b.consecutive_failures += 1
            finally:
                with self._lock:
                    b.last_probe_t = time.time()
                    healthy, draining = b.healthy, b.draining
                self.recorder.span('probe', t0, CLOCK(), host=b.addr,
                                   healthy=healthy, draining=draining)
        with self._lock:
            return {a: b.snapshot() for a, b in self._backends.items()}

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe()
            except Exception:
                _log_fleet_error('probe sweep')

    def eligible(self) -> List[str]:
        """Backends the ring may route to: healthy and not draining."""
        with self._lock:
            return [a for a, b in self._backends.items()
                    if b.healthy and not b.draining]

    # -- routing core --------------------------------------------------------

    @staticmethod
    def route_key(msg: Dict[str, Any]) -> str:
        """The consistent-hash key for one request: the first video's
        CONTENT hash (cache-key identity — repeat traffic for a video
        lands where its features are cached), the path itself when the
        file isn't readable yet (the backend will answer the error),
        or the family for vector searches."""
        paths = msg.get('video_paths') or []
        video = msg.get('video_path')
        if video is not None and not paths:
            paths = [video]
        if paths:
            from video_features_tpu.cache.key import hash_file
            try:
                return hash_file(str(paths[0]))
            except OSError:
                return str(paths[0])
        return f"family:{msg.get('family')}"

    def _remember_route(self, request_id: str, addr: str,
                        trace_id: Optional[str] = None,
                        attempted: Tuple[str, ...] = ()) -> None:
        with self._lock:
            self._routes[request_id] = (addr, trace_id,
                                        attempted or (addr,))
            self._route_order.append(request_id)
            while len(self._route_order) > ROUTE_HISTORY:
                self._routes.pop(self._route_order.popleft(), None)

    def _backend_call(self, addr: str,
                      msg: Dict[str, Any]) -> Dict[str, Any]:
        b = self._backends[addr]
        client = ServeClient(b.port, host=b.host,
                             connect_timeout_s=self.connect_timeout_s)
        return client._call(dict(msg))

    @staticmethod
    def _span_ids(ctx: Optional[TraceContext]) -> Dict[str, str]:
        """trace_id + a FRESH span_id for one router span (the pairing
        contract: every trace-scoped event names its own span)."""
        return ctx.child().attrs() if ctx is not None else {}

    def _observe_routed(self, t0: float, ok: bool) -> None:
        """Feed the router's SLO families: one latency observation and
        one outcome per routed request (failover walk included — the
        caller experienced the whole walk)."""
        self._latency_hist.observe(CLOCK() - t0)
        (self._req_completed if ok else self._req_failed).inc()

    def _route(self, key: str, msg: Dict[str, Any],
               on_success: Optional[Callable[[Dict[str, Any], str,
                                              Tuple[str, ...]], None]]
               = None,
               ctx: Optional[TraceContext] = None,
               ) -> Dict[str, Any]:
        """Walk the ring's failover order for ``key``, forwarding
        ``msg``; classify each failure by its structured code and
        either walk on (shed / connect_refused / deadline) or
        propagate. Returns the successful backend response, or the
        LAST failure as a structured error. ``on_success`` receives
        the response, the serving backend, and every backend the walk
        ATTEMPTED (trace assembly follows the same history)."""
        t_route = CLOCK()
        hosts = self.ring.hosts_for(key, eligible=self.eligible())
        if not hosts:
            with self._lock:
                self._rejected += 1
            self._observe_routed(t_route, ok=False)
            return protocol.error('no eligible fleet backend '
                                  '(all unhealthy or draining)',
                                  code=protocol.ERR_SHED)
        delay = self.backoff_base_s
        last: Optional[ServeError] = None
        attempted: List[str] = []
        for i, addr in enumerate(hosts[:self.max_attempts]):
            if i > 0:
                with self._lock:
                    self._failovers += 1
                t_f = CLOCK()
                time.sleep(delay)
                self.recorder.span('failover', t_f, CLOCK(),
                                   from_backend=attempted[-1],
                                   to_backend=addr, attempt=i,
                                   **self._span_ids(ctx))
                delay = min(delay * 2, self._BACKOFF_CAP_S)
            attempted.append(addr)
            t_call = CLOCK()
            try:
                resp = self._backend_call(addr, msg)
            except ServeError as e:
                self.recorder.span('backend_call', t_call, CLOCK(),
                                   backend=addr, attempt=i,
                                   error_code=e.code,
                                   **self._span_ids(ctx))
                last = e
                if e.code == protocol.ERR_CONNECT_REFUSED:
                    # fast member removal: don't wait for the probe
                    with self._lock:
                        b = self._backends[addr]
                        b.healthy = False
                        b.last_error = str(e)
                        b.consecutive_failures += 1
                if e.retryable:
                    continue
                break
            except (OSError, ValueError) as e:
                # transport surprise outside the classified set (reset
                # mid-read, undecodable response): treat as shed —
                # another host may serve it — but remember the text
                self.recorder.span('backend_call', t_call, CLOCK(),
                                   backend=addr, attempt=i,
                                   error_code=protocol.ERR_SHED,
                                   **self._span_ids(ctx))
                last = ServeError(f'{type(e).__name__}: {e}',
                                  code=protocol.ERR_SHED)
                continue
            self.recorder.span('backend_call', t_call, CLOCK(),
                               backend=addr, attempt=i,
                               **self._span_ids(ctx))
            with self._lock:
                self._routed[addr] = self._routed.get(addr, 0) + 1
            if on_success is not None:
                on_success(resp, addr, tuple(attempted))
            rid = resp.get('request_id')
            self.recorder.span('route', t_route, CLOCK(), backend=addr,
                               attempts=i + 1,
                               **({'request_id': rid} if rid else {}),
                               **self._span_ids(ctx))
            self._observe_routed(t_route, ok=True)
            return resp
        with self._lock:
            self._rejected += 1
        assert last is not None
        self.recorder.span('route', t_route, CLOCK(),
                           attempts=len(attempted),
                           error_code=last.code or protocol.ERR_INTERNAL,
                           **self._span_ids(ctx))
        self._observe_routed(t_route, ok=False)
        return protocol.error(str(last),
                              code=last.code or protocol.ERR_INTERNAL,
                              **{k: v for k, v in last.extra.items()
                                 if k not in ('ok', 'error', 'code')})

    # -- command handlers ----------------------------------------------------

    def submit(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            if self._draining:
                self._rejected += 1
                return protocol.error('draining',
                                      code=protocol.ERR_SHED)
        # adopt the caller's traceparent or mint one, and forward it on
        # EVERY loopback hop: the backend joins the same trace, so one
        # trace_id spans router + every attempted backend
        ctx = accept_traceparent(msg.get('traceparent'))
        msg = dict(msg)
        msg['traceparent'] = ctx.traceparent()

        def _remember(resp: Dict[str, Any], addr: str,
                      attempted: Tuple[str, ...]) -> None:
            rid = resp.get('request_id')
            if rid:
                self._remember_route(rid, addr, ctx.trace_id, attempted)
            # fused children route with the umbrella
            for child in (resp.get('requests') or {}).values():
                self._remember_route(child, addr, ctx.trace_id,
                                     attempted)
            resp['backend'] = addr

        return self._route(self.route_key(msg), msg,
                           on_success=_remember, ctx=ctx)

    def request_scoped(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """status: route by the remembered request_id → owner backend
        binding (content hash is not recoverable from an id)."""
        rid = msg.get('request_id')
        with self._lock:
            entry = self._routes.get(rid)
        if entry is None:
            return protocol.error(f'unknown request_id {rid!r}',
                                  code=protocol.ERR_NOT_FOUND)
        try:
            return self._backend_call(entry[0], msg)
        except ServeError as e:
            return protocol.error(str(e),
                                  code=e.code or protocol.ERR_INTERNAL)

    def assemble_trace(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Scatter-gather trace assembly: ask EVERY backend the request
        attempted (failover history, not just the owner) for its spans,
        stamp each event ``host=``, merge with the router's own spans
        for the trace, and return one ts-sorted timeline under the one
        trace_id. Per-host clocks are not aligned, so the sort is a
        presentation order — the ``host`` attr says where a span ran."""
        rid = msg.get('request_id')
        with self._lock:
            entry = self._routes.get(rid)
        if entry is None:
            return protocol.error(f'unknown request_id {rid!r}',
                                  code=protocol.ERR_NOT_FOUND)
        owner, trace_id, attempted = entry
        events: List[Dict[str, Any]] = []
        hosts: List[str] = []
        state = None
        for addr in attempted:
            try:
                resp = self._backend_call(
                    addr, {'cmd': protocol.CMD_TRACE,
                           'request_id': rid})
            except (ServeError, OSError, ValueError):
                # a backend that SHED the submit never admitted the
                # request — its not_found is expected, and even the
                # owner going down must degrade the trace to the spans
                # we can still reach, not fail the assembly
                if addr == owner:
                    _log_fleet_error(f'trace fetch from owner {addr}')
                continue
            hosts.append(addr)
            if addr == owner:
                state = resp.get('state')
                trace_id = resp.get('trace_id') or trace_id
            for ev in resp.get('events') or ():
                ev = dict(ev)
                args = dict(ev.get('args') or {})
                args['host'] = addr
                ev['args'] = args
                events.append(ev)
        for ev in self.recorder.snapshot():
            if ev.get('ph') == 'M':
                continue                  # router thread metas: noise
            args = ev.get('args') or {}
            if not ((trace_id and args.get('trace_id') == trace_id)
                    or args.get('request_id') == rid):
                continue
            ev = dict(ev)
            args = dict(args)
            args['host'] = 'router'
            ev['args'] = args
            events.append(ev)
        # metas first, then the joint timeline — cross-host ts are a
        # presentation order (same contract as tools/trace_view.py's
        # multi-file merge)
        events.sort(key=lambda e: (e.get('ph') != 'M',
                                   e.get('ts', 0)))
        return protocol.ok(request_id=rid, trace_id=trace_id,
                           state=state, events=events,
                           hosts=['router'] + hosts)

    def search(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return self._route(self.route_key(msg), msg)

    def forward_any(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Commands with no content affinity (index_status): any
        eligible backend, ring-ordered on a constant key for
        stability."""
        return self._route('fleet:any', msg)

    def metrics(self) -> Dict[str, Any]:
        """The fleet metrics document: router counters + the
        per-backend table (health, queue depth, cache hit rate — the
        ``tools/fleet_status.py`` surface). Backend metrics are
        fetched live from healthy hosts; a host that fails the fetch
        degrades to its probe row."""
        with self._lock:
            backends = {a: b.snapshot()
                        for a, b in self._backends.items()}
            doc: Dict[str, Any] = {
                'uptime_s': round(time.monotonic() - self._started_at, 3),
                'draining': self._draining,
                'hosts': list(self.ring.hosts),
                'routed': dict(self._routed),
                'failovers': self._failovers,
                'rejected': self._rejected,
            }
        for addr, row in backends.items():
            if not row['healthy']:
                continue
            try:
                m = self._backend_call(addr,
                                       {'cmd': protocol.CMD_METRICS})
                bm = m.get('metrics') or {}
                row['queue_depth'] = (bm.get('queue') or {}).get('depth')
                row['cache_hit_rate'] = \
                    (bm.get('cache') or {}).get('hit_rate')
                row['builds_compiled'] = \
                    (bm.get('warm_pool') or {}).get('builds_compiled')
                row['builds_loaded'] = \
                    (bm.get('warm_pool') or {}).get('builds_loaded')
            except (ServeError, OSError, ValueError):
                _log_fleet_error(f'metrics fetch from {addr}')
        doc['eligible'] = [a for a, r in backends.items()
                           if r['healthy'] and not r['draining']]
        doc['backends'] = backends
        # fleet-level SLO burn rates: every metrics assembly is an
        # evaluator tick (scrape-driven sampling, no extra thread)
        doc['slo'] = self.slo.tick()
        return {'fleet': doc}

    def metrics_prom(self) -> str:
        """The fleet's ONE Prometheus scrape: every backend's own
        exposition host-relabeled and merged (``fleet/aggregate.py``)
        plus the router's ``vft_fleet_*`` / ``vft_slo_*`` families. A
        backend that fails its scrape contributes no samples — its
        absence shows as ``vft_fleet_backend_up 0`` with an explicit
        ``vft_fleet_probe_age_seconds``, never as silently stale
        values."""
        deadline = min(0.5, self.connect_timeout_s)
        with self._lock:
            backends = list(self._backends.values())
        texts: Dict[str, Optional[str]] = {}
        for b in backends:
            if not b.healthy:
                texts[b.addr] = None
                continue
            try:
                texts[b.addr] = aggregate.scrape_prom(
                    b.host, b.port, deadline)
            except (ServeError, OSError, ValueError):
                _log_fleet_error(f'metrics scrape from {b.addr}')
                texts[b.addr] = None
        with self._lock:
            routed = dict(self._routed)
            failovers, rejected = self._failovers, self._rejected
            snaps = {a: b.snapshot() for a, b in self._backends.items()}
        # mirror the router's plain-int counters into registry series
        # by DELTA (counters only go up; the ints are the truth)
        for name, help_text, total, labels in (
                [('vft_fleet_failovers_total',
                  'failover walks to a next ring host', failovers, None),
                 ('vft_fleet_rejected_total',
                  'requests the router answered with a structured '
                  'error', rejected, None)]
                + [('vft_fleet_routed_total',
                    'requests routed per backend', n, {'host': a})
                   for a, n in routed.items()]):
            c = self.registry.counter(name, help_text, labels=labels)
            if total > c.value:
                c.inc(total - c.value)
        for addr, snap in snaps.items():
            self.registry.gauge(
                'vft_fleet_backend_up',
                '1 if the last probe of this backend succeeded',
                labels={'host': addr}).set(1 if snap['healthy'] else 0)
            self.registry.gauge(
                'vft_fleet_backend_draining',
                '1 if the backend reported draining on its last probe',
                labels={'host': addr}).set(1 if snap['draining'] else 0)
            if snap['probe_age_s'] is not None:
                self.registry.gauge(
                    'vft_fleet_probe_age_seconds',
                    'seconds since this backend was last probed '
                    '(staleness of its health row and of a missing '
                    'scrape)',
                    labels={'host': addr}).set(snap['probe_age_s'])
        self.slo.tick()
        return aggregate.merge_expositions(texts) + self.registry.render()

    # -- loopback listener ---------------------------------------------------

    def _dispatch(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        rejection = protocol.check_version(msg)
        if rejection is not None:
            return rejection
        cmd = msg.get('cmd')
        if cmd == protocol.CMD_PING:
            with self._lock:
                draining = self._draining
            return protocol.ok(draining=draining, v=protocol.VERSION,
                               fleet_hosts=len(self.ring))
        if cmd == protocol.CMD_SUBMIT:
            return self.submit(msg)
        if cmd == protocol.CMD_STATUS:
            return self.request_scoped(msg)
        if cmd == protocol.CMD_TRACE:
            return self.assemble_trace(msg)
        if cmd == protocol.CMD_SEARCH:
            return self.search(msg)
        if cmd == protocol.CMD_INDEX_STATUS:
            return self.forward_any(msg)
        if cmd == protocol.CMD_METRICS:
            return protocol.ok(metrics=self.metrics())
        if cmd == protocol.CMD_METRICS_PROM:
            return protocol.ok(text=self.metrics_prom())
        if cmd == protocol.CMD_DRAIN:
            with self._lock:
                self._draining = True
            return protocol.ok(draining=True)
        return protocol.error(
            f'unknown cmd {cmd!r}; known: {", ".join(protocol.COMMANDS)}',
            code=protocol.ERR_INVALID)

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return                     # socket closed: stopping
            threading.Thread(target=self._handle_conn, args=(conn,),
                             name='fleet-conn', daemon=True).start()

    def _handle_conn(self, conn) -> None:
        try:
            with conn:
                rfile = conn.makefile('rb')
                wfile = conn.makefile('wb')
                for line in rfile:
                    try:
                        msg = protocol.decode(line)
                        resp = self._dispatch(msg)
                    except Exception as e:
                        resp = protocol.error(f'{type(e).__name__}: {e}',
                                              code=protocol.ERR_INTERNAL)
                    try:
                        wfile.write(protocol.encode(resp))
                        wfile.flush()
                    except (OSError, ValueError):
                        return             # client went away mid-reply
        except OSError:
            pass                           # torn connection: next client

    # -- HTTP front door -----------------------------------------------------

    # structured code → HTTP status for propagated backend errors
    _CODE_STATUS: Dict[str, int] = {}

    @classmethod
    def _code_to_status(cls, code: Optional[str]) -> int:
        from video_features_tpu.ingress import http as h
        if not cls._CODE_STATUS:
            cls._CODE_STATUS.update({
                protocol.ERR_SHED: h.SERVICE_UNAVAILABLE,
                protocol.ERR_CONNECT_REFUSED: h.SERVICE_UNAVAILABLE,
                protocol.ERR_DEADLINE: h.SERVICE_UNAVAILABLE,
                protocol.ERR_INVALID: h.BAD_REQUEST,
                protocol.ERR_UNSUPPORTED: h.BAD_REQUEST,
                protocol.ERR_NOT_FOUND: h.NOT_FOUND,
                protocol.ERR_INTERNAL: h.INTERNAL_ERROR,
            })
        return cls._CODE_STATUS.get(code or '', h.INTERNAL_ERROR)

    def _handle_http(self, req, resp, conn) -> None:
        from video_features_tpu.ingress import http as h
        try:
            if req.method == 'GET' and req.path == '/healthz':
                # NO auth: load balancers probe this
                with self._lock:
                    table = {a: {'healthy': b.healthy,
                                 'draining': b.draining}
                             for a, b in self._backends.items()}
                    draining = self._draining
                resp.send_json(h.OK, {'ok': True, 'draining': draining,
                                      'fleet': True, 'backends': table})
                return
            tenant = self._http_auth.authenticate(req.headers)
            if tenant is None:
                resp.send_json(h.UNAUTHORIZED, {
                    'ok': False, 'error': 'unauthorized',
                    'message': 'missing or unknown API key '
                               '(Authorization: Bearer <key>)'})
                return
            if req.method == 'GET' and req.path == '/v1/metrics':
                resp.send_json(h.OK, {'ok': True,
                                      'metrics': self.metrics()})
                return
            if req.method == 'GET' and req.path == '/metrics':
                # the fleet's one Prometheus scrape target (same
                # content type as the daemons' ingress /metrics)
                resp.send(h.OK, self.metrics_prom().encode('utf-8'),
                          content_type='text/plain; version=0.0.4')
                return
            if req.method == 'GET' \
                    and req.path.startswith('/v1/requests/'):
                rid = req.path[len('/v1/requests/'):].strip('/')
                if rid.endswith('/trace'):
                    out = self.assemble_trace(
                        {'cmd': protocol.CMD_TRACE,
                         'request_id': rid[:-len('/trace')].strip('/')})
                else:
                    out = self.request_scoped(
                        {'cmd': protocol.CMD_STATUS, 'request_id': rid})
                status = h.OK if out.get('ok') \
                    else self._code_to_status(out.get('code'))
                resp.send_json(status, out)
                return
            if req.method == 'POST' \
                    and req.path in ('/v1/extract', '/v1/search'):
                body = req.json_body(16 * (1 << 20))
                acquired, reason = self._quota.acquire(tenant)
                if not acquired:
                    resp.send_json(
                        h.TOO_MANY_REQUESTS,
                        {'ok': False, 'error': reason,
                         'tenant': tenant.name})
                    return
                try:
                    if req.path == '/v1/extract':
                        msg = {'cmd': protocol.CMD_SUBMIT}
                        for k in protocol.SUBMIT_FIELDS:
                            if k in body:
                                msg[k] = body[k]
                        tp = req.headers.get('traceparent')
                        if tp and 'traceparent' not in msg:
                            msg['traceparent'] = tp
                        out = self.submit(msg)
                    else:
                        msg = dict(body)
                        msg['cmd'] = protocol.CMD_SEARCH
                        out = self.search(msg)
                finally:
                    # the router holds the concurrency unit only for
                    # the forward itself: completion lives on the
                    # backend, and its own ingress (when enabled)
                    # owns per-request lifetime quota
                    self._quota.release(tenant.name)
                status = h.OK if out.get('ok') \
                    else self._code_to_status(out.get('code'))
                resp.send_json(status, out)
                return
            raise h.HttpError(h.NOT_FOUND, 'not_found',
                              f'no fleet route {req.method} {req.path}')
        except h.HttpError as e:
            resp.send_json(e.status, e.body())


def fleet_main(argv: List[str]) -> int:
    """``python -m video_features_tpu fleet`` entry point."""
    import os
    import signal

    from video_features_tpu.config import parse_dotlist, split_fleet_config
    cli = parse_dotlist(argv)
    fleet_cfg, extra = split_fleet_config(cli)
    if extra:
        raise ValueError(
            f'unknown fleet keys: {sorted(extra)} — the router takes '
            f'only fleet_* knobs (backends own extraction config)')
    hosts = fleet_cfg['fleet_hosts']
    if not hosts:
        raise ValueError('fleet_hosts is required, e.g. '
                         'fleet_hosts=[127.0.0.1:9301,127.0.0.1:9302]')
    router = FleetRouter(
        hosts,
        host=fleet_cfg['fleet_host'],
        port=fleet_cfg['fleet_port'],
        http_host=fleet_cfg['fleet_http_host'],
        http_port=fleet_cfg['fleet_http_port'],
        auth_file=fleet_cfg['fleet_auth_file'],
        probe_interval_s=fleet_cfg['fleet_probe_interval_s'],
        max_attempts=fleet_cfg['fleet_max_attempts'],
        backoff_base_s=fleet_cfg['fleet_backoff_base_s'],
        connect_timeout_s=fleet_cfg['fleet_connect_timeout_s'],
        ring_replicas=fleet_cfg['fleet_ring_replicas'],
        slo_latency_p99_s=fleet_cfg['fleet_slo_latency_p99_s'],
        slo_availability=fleet_cfg['fleet_slo_availability'],
    ).start()
    done = threading.Event()

    def _graceful(signum, frame):
        router.stop()
        done.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    # machine-greppable endpoint line (tests and tooling scrape it,
    # same contract as the serve daemon's startup line)
    # vft-lint: ok=stdout-purity — documented startup line (fleet)
    print(f'fleet router on {router.host}:{router.port} '
          f'(pid {os.getpid()}; backends={",".join(router.ring.hosts)}, '
          f'eligible={len(router.eligible())})', flush=True)
    if router.http is not None:
        # vft-lint: ok=stdout-purity — documented startup line (fleet)
        print(f'fleet ingress on {router.http.host}:{router.http.port}',
              flush=True)
    done.wait()
    # vft-lint: ok=stdout-purity — shutdown line of the same contract
    print('fleet: stopped, exiting', flush=True)
    return 0
