"""The feature cache promoted to a two-level fleet tier.

``cache_l2_dir`` turns every ``FeatureCache`` open in the tree (the
CLI loop, the packed scheduler, every serve worker, the serve
admission path, the index service) into a :class:`TieredFeatureCache`:

  * **L1** — the host's own ``cache_dir``, byte-for-byte the existing
    store (this class IS a ``FeatureCache`` over it, so the manifest,
    ``on_evict`` coherence seam, GC, and stats all keep their
    single-host semantics);
  * **L2** — a shared directory every fleet host mounts (object-store
    shaped: get/put/head over content keys, atomic publish). A miss on
    host A for a video host B already extracted serves from L2
    byte-identically — NO decode, no model, no device — and promotes
    the entry into A's L1 so the next hit is local.

Consistency/trust model (docs/fleet.md): keys are content-addressed
(video sha256 × run fingerprint), so two hosts publishing the same key
wrote identical bytes by construction and last-writer-wins atomic
replace is safe; the manifest is the same append-converge op log the
single-host store uses across processes, just across hosts. Integrity
is enforced at BOTH levels with the same size-check/evict-corrupt
semantics — a torn or bit-rotted L2 entry is evicted and reads as a
miss, never served. The L2 carries no eviction pressure from request
paths (``max_bytes=None``); bounding it is the operator's
``tools/cache_gc.py`` run against the shared directory.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

from video_features_tpu.cache.store import FeatureCache, log_cache_error
from video_features_tpu.utils.output import make_path


class TieredFeatureCache(FeatureCache):
    """Local-L1 ``FeatureCache`` with a shared-directory L2 behind it."""

    _pair_instances: Dict[Tuple[str, str], 'TieredFeatureCache'] = {}
    _pair_lock = threading.Lock()

    @classmethod
    def get_pair(cls, cache_dir: str, l2_dir: str,
                 max_bytes: Optional[int] = None) -> 'TieredFeatureCache':
        """The process-wide tier for an (L1, L2) directory pair — same
        sharing policy as :meth:`FeatureCache.get`, keyed on the pair
        because the L1 dir alone no longer names the behavior."""
        key = (os.path.abspath(os.path.expanduser(str(cache_dir))),
               os.path.abspath(os.path.expanduser(str(l2_dir))))
        with cls._pair_lock:
            inst = cls._pair_instances.get(key)
            if inst is None:
                inst = cls._pair_instances[key] = cls(
                    key[0], key[1], max_bytes=max_bytes)
            elif max_bytes is not None:
                inst.max_bytes = int(max_bytes)
            return inst

    def __init__(self, cache_dir: str, l2_dir: str,
                 max_bytes: Optional[int] = None) -> None:
        super().__init__(cache_dir, max_bytes=max_bytes)
        # the shared tier is a plain FeatureCache over the shared dir:
        # its atomic publish, manifest convergence, and integrity
        # checks are exactly the cross-process story, now cross-host
        self.l2 = FeatureCache.get(l2_dir)
        self.peer_hits = 0        # L1 miss served from L2
        self.l2_publishes = 0     # local puts replicated into L2

    # -- core operations -----------------------------------------------------

    def contains(self, key: str) -> bool:
        return super().contains(key) or self.l2.contains(key)

    def fetch_to(self, key: str, out_root: str, video_path: str,
                 fingerprint: Optional[str] = None) -> bool:
        """L1 first; on miss, serve the peer's L2 entry and PROMOTE it
        into L1 (the freshly materialized output files are the put
        sources, so promotion costs one local copy, never a decode).
        A promotion failure degrades to an un-promoted hit — the bytes
        were already served."""
        if super().fetch_to(key, out_root, video_path, fingerprint):
            return True
        if not self.l2.fetch_to(key, out_root, video_path, fingerprint):
            return False
        with self._lock:
            self.peer_hits += 1
        exts = self.l2.entry_exts(key)
        if exts:
            files = {okey: (make_path(out_root, video_path, okey, ext), ext)
                     for okey, ext in exts.items()}
            try:
                super().put(key, files,
                            meta={'promoted_from': self.l2.cache_dir})
            except Exception:
                log_cache_error(f'L1 promotion of {key}')
        return True

    def put(self, key: str, files: Dict[str, Tuple[str, str]],
            meta: Optional[Dict[str, Any]] = None) -> None:
        """Publish locally, then into the shared tier — so a peer's
        very next miss on this key is an L2 hit. An L2 publish failure
        (shared mount gone, quota) degrades to local-only and is
        reported; it must never fail the extraction that produced the
        bytes."""
        super().put(key, files, meta)
        try:
            self.l2.put(key, files, meta)
            with self._lock:
                self.l2_publishes += 1
        except Exception:
            log_cache_error(f'L2 publish of {key} ({self.l2.cache_dir})')

    # -- accounting ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        with self._lock:
            out['peer_hits'] = self.peer_hits
            out['l2_publishes'] = self.l2_publishes
        out['l2'] = self.l2.stats()
        return out
