"""Fleet-wide Prometheus aggregation: scrape → relabel → merge.

One scrape target for the whole fleet: the router asks every backend
for its own exposition text (the ``metrics_prom`` command each daemon
already serves), injects a ``host="<addr>"`` label into every sample
so per-host series stay distinguishable after the merge, regroups the
``# HELP`` / ``# TYPE`` headers so each family appears ONCE, and
appends the router's own ``vft_fleet_*`` / ``vft_slo_*`` families.
A Prometheus agent then needs a single target (the router's
``/metrics`` route) instead of N backend addresses that churn as the
fleet scales — exactly the "metrics already exported" surface ROADMAP
item 2's elastic membership consumes.

Two deliberate properties:

  * **Scrapes ride the probe deadline.** A backend that accepts and
    never answers must cost the aggregate half a second, not wedge the
    fleet's only scrape target — same hard-deadline policy (and the
    same raw-socket shape) as the router's health probe.
  * **Staleness is explicit, not implicit.** A backend that fails its
    scrape contributes NO samples (its last values are never replayed
    as if fresh); the router's ``vft_fleet_backend_up`` /
    ``vft_fleet_probe_age_seconds`` gauges say which hosts are missing
    and how old their last probe is.

Merging happens at the TEXT level — the backends' exposition is parsed
line-wise, not re-ingested into a registry — so histogram bucket
layouts, counter monotonicity, and escaping survive byte-for-byte from
each daemon; the only rewrite is the injected ``host`` label.
"""
from __future__ import annotations

import re
from typing import Dict, List, Mapping, Optional, Tuple

from video_features_tpu.obs.metrics import _escape
from video_features_tpu.serve import protocol

# one exposition sample line: name, optional {labels}, value (and an
# optional timestamp) kept verbatim in `rest`
_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r'(?P<rest>\s.+)$')

# sample-name suffixes that belong to a histogram/summary family whose
# HELP/TYPE headers carry the BASE name
_FAMILY_SUFFIXES = ('_bucket', '_sum', '_count')


def scrape_prom(host: str, port: int, timeout_s: float) -> str:
    """One backend's exposition text under a HARD deadline (connect and
    read) — the router's probe-call shape, because ``ServeClient``
    leaves reads unbounded and the fleet's one scrape target must not
    inherit a wedged backend's patience."""
    import socket
    with socket.create_connection((host, port),
                                  timeout=timeout_s) as conn:
        conn.settimeout(timeout_s)
        conn.sendall(protocol.encode({'cmd': protocol.CMD_METRICS_PROM,
                                      'v': protocol.VERSION}))
        with conn.makefile('rb') as rfile:
            line = rfile.readline()
    if not line:
        raise ConnectionError('backend closed the metrics connection')
    resp = protocol.decode(line)
    if not resp.get('ok'):
        raise ValueError(f'metrics_prom failed: {resp.get("error")}')
    return str(resp.get('text') or '')


def _family_of(sample_name: str, types: Dict[str, str]) -> str:
    """The family a sample line belongs to: its own name, or — for
    ``_bucket``/``_sum``/``_count`` suffixes whose base name has a
    declared histogram/summary TYPE — the base name."""
    for suffix in _FAMILY_SUFFIXES:
        if sample_name.endswith(suffix):
            base = sample_name[:-len(suffix)]
            if types.get(base) in ('histogram', 'summary'):
                return base
    return sample_name


def merge_expositions(per_host: Mapping[str, Optional[str]]) -> str:
    """Merge per-backend exposition texts into one, every sample
    relabeled with ``host=<addr>``.

    ``per_host`` maps backend addr → its scraped text, or ``None`` for
    a host whose scrape failed (it contributes nothing — staleness is
    reported by the router's own gauges, not by replaying old values).
    Families are emitted once each (first host's HELP/TYPE wins; the
    daemons all render the same registry code, so headers agree),
    sorted by name for a stable scrape; within a family, samples keep
    per-host order. Returns '' when nothing was scraped.
    """
    # family → {'help': line|None, 'type': line|None, 'samples': [...]}
    families: Dict[str, Dict[str, object]] = {}

    def fam(name: str) -> Dict[str, object]:
        f = families.get(name)
        if f is None:
            f = families[name] = {'help': None, 'type': None,
                                  'samples': []}
        return f

    for host, text in per_host.items():
        if not text:
            continue
        host_label = f'host="{_escape(host)}"'
        types: Dict[str, str] = {}
        for raw in text.splitlines():
            line = raw.rstrip()
            if not line:
                continue
            if line.startswith('# HELP '):
                parts = line.split(' ', 3)
                if len(parts) >= 3 and fam(parts[2])['help'] is None:
                    fam(parts[2])['help'] = line
                continue
            if line.startswith('# TYPE '):
                parts = line.split(' ', 3)
                if len(parts) >= 4:
                    types[parts[2]] = parts[3]
                    if fam(parts[2])['type'] is None:
                        fam(parts[2])['type'] = line
                continue
            if line.startswith('#'):
                continue                       # free-form comment
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue                       # not exposition — drop
            labels = m.group('labels')
            merged = host_label if not labels else \
                f'{host_label},{labels}'
            name = m.group('name')
            fam(_family_of(name, types))['samples'].append(
                f'{name}{{{merged}}}{m.group("rest")}')

    lines: List[str] = []
    for name in sorted(families):
        f = families[name]
        if not f['samples']:
            continue                           # headers with no data
        if f['help']:
            lines.append(str(f['help']))
        if f['type']:
            lines.append(str(f['type']))
        lines.extend(f['samples'])             # type: ignore[arg-type]
    return '\n'.join(lines) + '\n' if lines else ''
