"""The AOT executable store as the fleet's shared artifact tier.

``aot_l2_dir`` turns every ``ExecStore`` open (serve workers, packed
CLI runs, the index service's query program) into a
:class:`TieredExecStore`: the host's own ``aot_dir`` stays the L1 —
this class IS an ``ExecStore`` over it — and a shared directory every
fleet host mounts becomes the artifact tier behind it.

Why this is safe with zero coordination: ``exec_digest`` already keys
on the program's StableHLO sha256 (the identity PROGRAMS.lock.json
pins) plus the lane, jax version, backend platform, device kind, and
host ISA. Two hosts with matching environments compute the SAME digest
for the same program, so:

  * **publish-on-compile** — a compile anywhere in the fleet lands the
    serialized executable in the shared tier (local put, then shared
    put, both atomic-replace idempotent);
  * **pull-on-miss** — a freshly provisioned host's first ``fetch``
    misses its empty L1, hits the shared tier, re-publishes the payload
    locally (so the next boot is a local load), and serves its first
    request compile-free — ``builds_compiled == 0``;
  * **silent recompile on drift** — a host whose environment differs
    (jax upgrade, different device kind or ISA) simply computes a
    digest nothing published: the miss is structural, the runtime
    compiles as it always did, and ``metas_for`` still surfaces the
    near-miss for the drift diagnostics.

Counters fold into the existing ``vft_aot_*`` families: the tier's
stats are the L1 stats plus ``pulled`` / ``published`` and an ``l2``
sub-document; ``merge_exec_stats`` sums what it knows and ignores the
rest. Integrity at both levels is the store's own size-check /
evict-corrupt path; a payload that fails to DESERIALIZE after a pull
is evicted from BOTH tiers (identical bytes — a poisoned shared entry
must not re-poison every cold host). The shared tier carries no inline
eviction pressure (``max_bytes=None``); bounding it is
``tools/aot_gc.py`` against the shared directory.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

from video_features_tpu.aot.store import ExecStore, log_aot_error


class TieredExecStore(ExecStore):
    """Local-L1 ``ExecStore`` with a shared artifact tier behind it."""

    _pair_instances: Dict[Tuple[str, str], 'TieredExecStore'] = {}
    _pair_lock = threading.Lock()

    @classmethod
    def get_pair(cls, aot_dir: str, l2_dir: str,
                 max_bytes: Optional[int] = None) -> 'TieredExecStore':
        """The process-wide tier for an (L1, shared) directory pair —
        same sharing policy as :meth:`ExecStore.get`."""
        key = (os.path.abspath(os.path.expanduser(str(aot_dir))),
               os.path.abspath(os.path.expanduser(str(l2_dir))))
        with cls._pair_lock:
            inst = cls._pair_instances.get(key)
            if inst is None:
                inst = cls._pair_instances[key] = cls(
                    key[0], key[1], max_bytes=max_bytes)
            elif max_bytes is not None:
                inst.max_bytes = int(max_bytes)
            return inst

    def __init__(self, aot_dir: str, l2_dir: str,
                 max_bytes: Optional[int] = None) -> None:
        super().__init__(aot_dir, max_bytes=max_bytes)
        self.l2 = ExecStore.get(l2_dir)
        self.pulled = 0           # L1 miss served from the shared tier
        self.published = 0        # local puts replicated into it

    # -- core operations -----------------------------------------------------

    def contains(self, digest: str) -> bool:
        return super().contains(digest) or self.l2.contains(digest)

    def metas_for(self, program_sha: str) -> list:
        """Union of both tiers (deduplicated) — a cold host's drift
        diagnostics must see what the FLEET holds for the program, not
        its own empty L1."""
        seen = []
        for meta in super().metas_for(program_sha) \
                + self.l2.metas_for(program_sha):
            if meta not in seen:
                seen.append(meta)
        return seen

    def fetch(self, digest: str) -> Optional[bytes]:
        """L1 first; on miss, pull from the shared tier and re-publish
        locally under the peer's recorded meta (pull-on-miss). A failed
        local re-publish degrades to serving the pulled bytes — the
        next boot pulls again."""
        payload = super().fetch(digest)
        if payload is not None:
            return payload
        payload = self.l2.fetch(digest)
        if payload is None:
            return None
        with self._lock:
            self.pulled += 1
        try:
            super().put(digest, payload, meta=self.l2.meta_for(digest))
        except Exception:
            log_aot_error(f'local re-publish of pulled {digest[:12]}')
        return payload

    def put(self, digest: str, payload: bytes,
            meta: Optional[Dict[str, Any]] = None) -> None:
        """Publish locally, then into the shared tier
        (publish-on-compile). A shared publish failure degrades to
        local-only and is reported — it must never fail the build that
        produced the executable."""
        super().put(digest, payload, meta)
        try:
            self.l2.put(digest, payload, meta)
            with self._lock:
                self.published += 1
        except Exception:
            log_aot_error(f'shared publish of {digest[:12]} '
                          f'({self.l2.aot_dir})')

    def evict_corrupt(self, digest: str) -> None:
        """Purge BOTH tiers: a payload that failed to deserialize was
        byte-identical in each, and leaving the shared copy would
        re-poison every cold host that pulls it."""
        super().evict_corrupt(digest)
        try:
            self.l2.evict_corrupt(digest)
        except Exception:
            log_aot_error(f'shared corrupt-evict of {digest[:12]}')

    # -- accounting ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        out = super().stats()
        with self._lock:
            out['pulled'] = self.pulled
            out['published'] = self.published
        out['l2'] = self.l2.stats()
        return out
