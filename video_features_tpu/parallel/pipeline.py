"""Sharded extraction pipeline: the fused two-stream step over a device mesh.

Where the reference runs one python loop per GPU process (reference
main.py:47-48) and scales by launching more processes, this module compiles
ONE program over a (data, time) mesh:

  * stack windows shard over ``data`` (in-graph data parallelism);
  * RAFT flow pairs additionally spread over ``time`` (sequence parallelism
    over the temporal axis — the pairs are independent, so XLA inserts only
    the reshard collectives at the sub-graph boundary, and they ride ICI);
  * params are replicated (SURVEY.md §2.3 — nets are small; TP buys nothing).

Outputs land fully replicated so the host can write `.npy` files under the
same idempotent-output contract the reference uses for elasticity.
"""
from __future__ import annotations

from typing import Tuple

import jax
from jax.sharding import Mesh

from video_features_tpu.extract.i3d import fused_two_stream_step
from video_features_tpu.parallel.mesh import (
    batch_sharding, pair_sharding, replicated,
)


def build_sharded_two_stream_step(mesh: Mesh,
                                  streams: Tuple[str, ...] = ('rgb', 'flow'),
                                  donate_stacks: bool = False,
                                  pins=None, raft_iters=None):
    """jit-compiled ``step(params, stacks, pads, crop_size=…,
    resize_to=…)`` over ``mesh``.

    ``stacks`` is (B, stack+1, H, W, 3) with B divisible by the data-axis
    size; ``pads`` is the static (top, bottom, left, right) /8 padding tuple
    from raft.pad_to_multiple; ``resize_to`` (static; None = off) runs the
    bit-exact in-graph PIL resize (device_resize) before everything else —
    per-sample work that composes with the data sharding, though each
    distinct (pads, crop_size, resize_to) triple is its own executable.
    Returns {stream: (B, 1024)} replicated.

    pjit rejects kwargs when in_shardings is given, so the static args are
    positional here (argnums 2/3/4) and ``streams`` is baked per-build.
    """
    def constrain_pairs(t: jax.Array) -> jax.Array:
        return jax.lax.with_sharding_constraint(t, pair_sharding(mesh))

    # the mesh's devices say where the program runs — drive the RAFT
    # corr-lookup dispatch from them, not the process default backend
    platform = mesh.devices.flat[0].platform

    def step(params, stacks, pads, crop_size, resize_to):
        kw = {} if raft_iters is None else {'raft_iters': raft_iters}
        return fused_two_stream_step(params, stacks, pads, streams,
                                     constrain_pairs=constrain_pairs,
                                     crop_size=crop_size, platform=platform,
                                     pins=pins, resize_to=resize_to, **kw)

    jitted = jax.jit(
        step,
        static_argnums=(2, 3, 4),
        in_shardings=(replicated(mesh), batch_sharding(mesh)),
        out_shardings=replicated(mesh),
        donate_argnums=(1,) if donate_stacks else (),
    )

    def call(params, stacks, pads, crop_size=224, resize_to=None):
        # resize_to: the in-graph bit-exact PIL resize (device_resize) —
        # per-sample work, so it composes with the data sharding with no
        # extra collectives
        return jitted(params, stacks, pads, crop_size, resize_to)

    return call


def put_replicated(mesh: Mesh, params):
    """Place a params pytree on every device of the mesh."""
    return jax.device_put(params, replicated(mesh))


def put_batch(mesh: Mesh, batch):
    """Shard a host batch over the data axis of the mesh."""
    return jax.device_put(batch, batch_sharding(mesh))


def setup_data_parallel(device: str, batch_size: int, params):
    """One-stop in-graph DP setup for a batch-sharding extractor.

    Returns ``(mesh, global_batch, replicated_params, put_batch_fn)``: a
    data-only mesh over this host's local devices of ``device``'s platform,
    the batch size rounded up to fill the data axis, the params placed on
    every device, and a batch-placement callable. Feeding jit functions
    these shardings makes XLA compile one pjit program — no per-extractor
    sharding code needed.
    """
    from functools import partial

    from video_features_tpu.parallel.mesh import (
        make_mesh, round_batch_to_data_axis,
    )
    from video_features_tpu.utils.device import jax_devices_all

    mesh = make_mesh(devices=jax_devices_all(device), time_parallel=1)
    return (mesh, round_batch_to_data_axis(batch_size, mesh),
            put_replicated(mesh, params), partial(put_batch, mesh))
