"""Multi-host work distribution: the shared-nothing video-list contract.

The reference distributes work by (a) shuffling the path list per process so
concurrent workers rarely collide and (b) relying on idempotent output files
plus is_already_exist re-checks to make collisions benign (reference
utils/utils.py:151-176, models/_base/base_extractor.py:77-81,100-132).

The TPU build keeps that contract — it is what makes workers elastic and
restartable — but replaces the probabilistic shuffle with a deterministic
interleaved shard per host, so N healthy hosts do zero duplicate work while
a dead host's videos are still picked up by any worker re-run with the full
list (the skip-if-exists check makes re-processing free).
"""
from __future__ import annotations

import random
from typing import List, Optional, Sequence

import jax


def shard_worklist(paths: Sequence[str],
                   shard_id: Optional[int] = None,
                   num_shards: Optional[int] = None) -> List[str]:
    """Deterministic interleaved shard of the video list for this host.

    Defaults to jax's multi-host identity (process_index/process_count), so
    the same launch command works on every host of a pod — the reference
    needs a manually varied ``device=`` per terminal instead
    (README.md:70-78).
    """
    if num_shards is None:
        num_shards = jax.process_count()
    if shard_id is None:
        shard_id = jax.process_index()
    if not 0 <= shard_id < num_shards:
        raise ValueError(f'shard_id {shard_id} out of range [0, {num_shards})')
    # Interleaved (round-robin) keeps per-shard work balanced even when the
    # list is sorted by size/class, unlike contiguous block splits.
    return list(paths[shard_id::num_shards])


def shuffled(paths: Sequence[str], seed: Optional[int] = None) -> List[str]:
    """Opt-in shuffle for heterogeneous-worker runs (the reference's default
    collision-avoidance strategy, utils/utils.py:175-176)."""
    out = list(paths)
    random.Random(seed).shuffle(out)
    return out
