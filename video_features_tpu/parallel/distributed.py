"""Multi-host runtime initialization for TPU pods.

The reference has no collective backend at all — its multi-node story is
"run another process with another device flag over a shared filesystem"
(reference README.md:70-84). Here multi-host runs are first-class:

  * :func:`initialize` brings up jax's distributed runtime (coordinator
    discovery, ICI/DCN mesh wiring) — on Cloud TPU pods
    ``jax.distributed.initialize()`` autodetects everything from the
    environment, and each host then sees its local chips in
    ``jax.local_devices()`` and the full slice in ``jax.devices()``;
  * combined with :func:`~video_features_tpu.parallel.worklist.shard_worklist`
    (deterministic per-host shard of the video list) and the idempotent
    output contract, the same launch command works on every host of a pod:

        # on every host of a v5e-64 slice
        python -m video_features_tpu feature_type=i3d multihost=true \\
            file_with_video_paths=paths.txt output_path=gs://bucket/feats

    In-graph collectives (the data/time mesh of parallel.mesh) ride ICI
    within the slice; nothing but the work list and output files crosses
    DCN — the sharding layout that keeps collectives off the slow network.
"""
from __future__ import annotations

import warnings
from typing import Optional


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the jax distributed runtime (no-op if already initialized).

    With no arguments, autodetects from the TPU-pod / cluster environment
    (the common case). Arguments are for manual clusters: a
    ``host:port`` coordinator, world size, and this host's rank.
    """
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs['coordinator_address'] = coordinator_address
    if num_processes is not None:
        kwargs['num_processes'] = num_processes
    if process_id is not None:
        kwargs['process_id'] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if 'already initialized' in str(e).lower():
            return
        raise
    except ValueError:
        if kwargs:
            raise
        # Not on a pod/cluster (autodetection found no coordinator). A
        # single-process run needs no distributed runtime: process_count()
        # is 1 and the worklist shard is the whole list.
        warnings.warn('multihost: no cluster environment detected — '
                      'continuing as a single-process run')
