"""Sequence-parallel (ring) attention over a device mesh axis.

The reference has no sequence parallelism (SURVEY.md §2.3 — its long-video
story is sliding windows on one device). Here, token sequences that exceed
one chip's HBM — e.g. a whole video's worth of temporal tokens — shard over
the mesh's ``time`` axis, and attention runs as a KV ring over ICI
(:func:`video_features_tpu.ops.attention.ring_attention`).

``sequence_sharded_attention`` is the array-level entry: give it global
(B, S, H, D) arrays (or arrays already placed with a sequence sharding) and
a mesh; it shard_maps the ring kernel over the chosen axis.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from video_features_tpu.utils.device import shard_map

from video_features_tpu.ops.attention import ring_attention
from video_features_tpu.parallel.mesh import TIME_AXIS


def sequence_sharding(mesh: Mesh, axis: str = TIME_AXIS) -> NamedSharding:
    """Sharding that splits the sequence dim of (B, S, H, D) over ``axis``."""
    return NamedSharding(mesh, P(None, axis, None, None))


def sequence_sharded_attention(mesh: Mesh, q: jax.Array, k: jax.Array,
                               v: jax.Array, axis: str = TIME_AXIS,
                               scale: Optional[float] = None) -> jax.Array:
    """Ring attention with q/k/v sequence-sharded over ``mesh[axis]``.

    The axis size must divide S. The result carries the same sequence
    sharding as the inputs; only ring-neighbor ppermute traffic crosses
    devices — no all-gather, so per-device memory stays O(S/n · S/n) for
    scores and O(S/n) for KV.
    """
    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
