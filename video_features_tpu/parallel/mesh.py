"""Device-mesh construction for sharded extraction.

The reference scales by launching N independent single-GPU processes over a
shared filesystem (reference README.md:70-84, utils/utils.py:151-176 — the
shuffled work list IS its distribution layer). The TPU-native design keeps
that shared-nothing elasticity contract *across hosts* (see
:mod:`.worklist`) and adds *in-graph* parallelism within a slice:

  * ``data`` axis — data parallelism over stack windows / frame batches
    (the reference's per-process parallelism, moved inside one XLA program);
  * ``time`` axis — sequence parallelism over temporal flow pairs: a stack
    of S+1 frames yields S independent RAFT pairs, and long videos yield
    many stacks, so the temporal dimension shards cleanly with no halo
    (SURVEY.md §5.7: temporal tiling is the long-context analog here).

Collectives ride ICI inside the mesh; DCN/filesystem only carries the
work-list and the output files.
"""
from __future__ import annotations

import warnings
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
TIME_AXIS = 'time'


def factor_mesh_shape(n: int, time_parallel: Optional[int] = None) -> Tuple[int, int]:
    """Split ``n`` devices into (data, time) axis sizes.

    Defaults to the largest power-of-two time axis ≤ 2 — flow pairs within a
    stack are plentiful (stack_size ≥ 10), but data parallelism over stacks
    has better arithmetic intensity per shard, so it gets the larger axis.
    """
    if time_parallel is None:
        time_parallel = 2 if n % 2 == 0 and n > 1 else 1
    if n % time_parallel != 0:
        raise ValueError(f'{n} devices do not factor into time={time_parallel}')
    return n // time_parallel, time_parallel


def make_mesh(n_devices: Optional[int] = None,
              time_parallel: Optional[int] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 2-D (data, time) mesh over the available (or given) devices.

    ``n_devices=0`` auto-detects: the mesh spans EVERY available (or
    given) device — the ``mesh_devices=0`` config spelling for "use the
    whole slice". An over-ask raises here with the device counts named,
    instead of surfacing later as an opaque XLA placement error.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None and n_devices != 0:
        if n_devices > len(devices):
            raise ValueError(
                f'requested {n_devices} devices, have {len(devices)}')
        devices = devices[:n_devices]
    shape = factor_mesh_shape(len(devices), time_parallel)
    if (shape[0] > 1 and shape[1] > 1
            and not (hasattr(jax.lax, 'pvary') or hasattr(jax.lax, 'pcast'))):
        # jax 0.4.x: the (data>1, time>1) sharded two-stream program was
        # measured to diverge on the flow stream (tests/test_parallel.py
        # test_sharded_two_stream_step_matches_single_device documents
        # the number) — the time-axis resharding this layer was validated
        # against postdates 0.4. Surface it loudly; data-only meshes
        # (time_parallel=1) are verified on 0.4.x.
        warnings.warn(
            '(data, time) meshes are numerically unvalidated on this '
            'jax version — flow-stream divergence was measured on '
            '0.4.x. Use time_parallel=1 (data-only) or upgrade jax.')
    grid = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(grid, (DATA_AXIS, TIME_AXIS))


def round_batch_to_data_axis(batch_size: int, mesh: Mesh) -> int:
    """Smallest multiple of the mesh's data-axis size ≥ ``batch_size`` —
    the global batch an in-graph data-parallel extractor compiles for."""
    d = mesh.shape[DATA_AXIS]
    return -(-batch_size // d) * d


def plan_device_batch(capacity: int, mesh: Mesh) -> int:
    """Global packed batch for a data-parallel mesh: ``capacity`` window
    slots PER device shard (the per-device batch the family's step was
    tuned for), so the packer plans ``capacity × ndev`` slots and every
    device runs at its single-chip batch shape. Raises a clear error —
    not a downstream XLA shape error — when the plan can't fill a shard.
    """
    ndev = mesh.shape[DATA_AXIS]
    capacity = int(capacity)
    if capacity < 1:
        raise ValueError(
            f'mesh-sharded packed batch planning needs capacity >= 1 per '
            f'device shard (got capacity={capacity} over {ndev} '
            f'data-parallel devices): capacity × ndev is the global device '
            f'batch — raise batch_size or lower mesh_devices')
    return capacity * ndev


def shard_error(batch: int, mesh: Mesh) -> Optional[str]:
    """Why a GLOBAL batch of ``batch`` rows cannot shard over the mesh's
    data axis, or None when it can. The non-raising form of
    :func:`require_shardable` — the vft-programs shardability rule
    (``analysis/programs.py``) turns the message into a finding instead
    of an exception."""
    ndev = mesh.shape[DATA_AXIS]
    if batch % ndev != 0 or batch // ndev < 1:
        return (
            f'packed batch {batch} cannot shard over {ndev} data-parallel '
            f'devices: the global batch must be a positive multiple of the '
            f'device count (capacity × ndev planning — see '
            f'plan_device_batch)')
    return None


def require_shardable(batch: int, mesh: Mesh) -> int:
    """Validate that a GLOBAL batch splits evenly over the data axis,
    raising a named error instead of letting ``device_put`` fail with an
    XLA sharding/shape error. Returns the per-shard capacity."""
    err = shard_error(batch, mesh)
    if err is not None:
        raise ValueError(err)
    return batch // mesh.shape[DATA_AXIS]


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for params: one full copy per device (models are ≤100s MB —
    SURVEY.md §2.3: tensor parallelism is not needed, replicate per chip)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over the data axis (stack windows / frames)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def pair_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over BOTH axes — each device gets a contiguous
    run of rows; no halo exchange is needed because all-pairs correlation
    is local to a pair. Used for the (B·S, …) flow-pair/cnet tensors (even
    split) and the B·(S+1) unique-frames tensor feeding fnet, where the +1
    halo leaves the last shards padded by ≤1 frame (see
    raft.forward_stack_pairs)."""
    return NamedSharding(mesh, P((DATA_AXIS, TIME_AXIS)))
