"""Parallelism layer: device meshes, sharded pipelines, multi-host worklists.

See SURVEY.md §2.3 for the accounting of what the reference does (shared-
nothing multi-process data parallelism only) and what this layer adds
(in-graph DP over stacks + sequence parallelism over temporal flow pairs,
with XLA collectives over ICI).
"""
from video_features_tpu.parallel.distributed import (  # noqa: F401
    initialize,
)
from video_features_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS, TIME_AXIS, batch_sharding, factor_mesh_shape, make_mesh,
    pair_sharding, plan_device_batch, replicated, require_shardable,
    round_batch_to_data_axis,
)
from video_features_tpu.parallel.packing import (  # noqa: F401
    VideoTask, packed_batches, run_packed,
)
from video_features_tpu.parallel.pipeline import (  # noqa: F401
    build_sharded_two_stream_step, put_batch, put_replicated,
    setup_data_parallel,
)
from video_features_tpu.parallel.ring import (  # noqa: F401
    sequence_sharded_attention, sequence_sharding,
)
from video_features_tpu.parallel.worklist import (  # noqa: F401
    shard_worklist, shuffled,
)
