"""Corpus-level packed execution: batch-major scheduling across videos.

The reference (and, until this module, this framework) runs a video-major
outer loop: every video separately streams its windows into the compiled
device step, so at corpus shapes (K400: a handful of stack windows per
clip) the last batch of every video runs mostly padded and every video
pays the pipeline ramp (prefetch fill, cache warm, H2D latency) again.

This module inverts the loop — batch-major over the whole worklist:

  * a cross-video window stream (``extract.streaming.
    stream_windows_across_videos``) drains clip stacks / frames from one
    video after another, with per-video fault isolation;
  * a decode-ahead thread (``io.video.prefetch_across_videos``) keeps the
    decoder busy across video boundaries under a bounded window buffer;
  * the packer fills every device batch to capacity with
    (video, window_idx) provenance, grouping by window geometry so mixed
    corpora still feed fixed-shape executables;
  * the device loop is asynchronous on BOTH sides: ``packed_step`` only
    DISPATCHES (device arrays out, no forced readback), and a bounded
    in-flight queue (the ``inflight`` knob, default 2; 1 = synchronous)
    defers each batch's D2H readback until the next batch has
    dispatched — so readback, row scatter, and output writes overlap
    device compute instead of stalling it;
  * features scatter back into per-video accumulators that flush as each
    video completes (NOT necessarily in worklist order — a video whose
    geometry pool can't fill must not block videos behind it) through the
    UNCHANGED per-video output contract (``is_already_exist`` skip,
    idempotent ``action_on_extraction`` writes, identical filenames) —
    the same files as the per-video loop, except the chip stays fed.

Composition: batches go through ``BaseExtractor.put_input``, so
``data_parallel=true`` sharding works unchanged; the worklist arrives
already sharded per host in multihost runs (``cli.py``), so packing is a
per-host concern and needs no cross-host coordination.

Since the serving layer (``serve/``) the worklist no longer has to be a
static list: ``run_packed`` consumes its ``video_paths`` iterable lazily
(it may block — e.g. on a request queue) and accepts pre-built
``VideoTask`` objects, so dynamically arriving requests pack into the
same device batches as a static corpus. The ``FLUSH`` sentinel bounds
latency under dynamic arrivals: when the source momentarily runs dry it
can push ``FLUSH`` through the stream to force the partial geometry
pools out as padded batches instead of holding a lone request's windows
hostage until the next request happens to share its geometry.
"""
from __future__ import annotations

import logging as _logging
import sys
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from video_features_tpu.obs.context import trace_attrs, trace_ids_of
from video_features_tpu.obs.events import event
from video_features_tpu.utils.tracing import NULL_TRACER, Tracer

# Stream sentinel: "no more input for now — flush partial pools". Yielded
# by dynamic sources (the serve request feed) between arrival bursts;
# passes through the windower/prefetch layers untouched and is consumed
# by ``packed_batches``. Identity-compared everywhere (``is FLUSH``).
FLUSH = object()

def _request_id(task) -> Optional[str]:
    """The originating request id of a serve task (None for CLI tasks) —
    threaded onto span/instant events so a Perfetto timeline groups by
    request as well as by video."""
    req = getattr(task, 'request', None)
    return getattr(req, 'id', None)


# Stream marker: "a video exhausted without emitting any window" (resume
# skip, zero-window clip, failed open). It must REACH the consumer — all
# finalization runs on the consumer thread, and with no batch to carry the
# news a dynamic stream would otherwise not finalize such videos until
# drain (an all-skip request would hang). ``packed_batches`` forwards it
# as a batchless ``(None, [], 0)`` item that triggers a sweep.
NUDGE = object()


def segment_name(path: str, segment) -> str:
    """Output-naming path for a ``(start_s, end_s)`` segment extraction:
    the video's stem gains a ``_seg<start>-<end>ms`` suffix (millisecond
    ints — dots in a stem would truncate under ``Path(...).stem``), so a
    partial-range extraction NEVER collides with the full video's output
    files (or another range's) in a shared output root. The same
    quantization keys the cache (``cache.key.video_cache_key``)."""
    if segment is None:
        return str(path)
    from pathlib import Path as _Path
    p = _Path(path)
    start_ms = int(round(float(segment[0]) * 1000))
    end_ms = int(round(float(segment[1]) * 1000))
    return str(p.with_name(f'{p.stem}_seg{start_ms}-{end_ms}ms{p.suffix}'))


class VideoTask:
    """Per-video scheduling + scatter-back state for the packed pipeline.

    ``emitted`` counts windows the decode side yielded, ``done`` counts
    windows whose features have scattered back; the video is complete when
    ``exhausted and done == emitted``. ``skipped`` (resume hit) and
    ``failed`` both finalize without writing. ``rows``/``meta_rows`` accumulate
    the scattered per-window feature rows (in window order — the packer
    preserves per-video FIFO because a video's windows share one geometry
    pool); ``info`` carries video-level metadata (e.g. fps) set by the
    extractor's window stream. ``out_root`` (None for CLI worklists)
    overrides the extractor's ``output_path`` for this one video — the
    serving layer routes concurrent requests with different output roots
    through one shared warm extractor.
    """

    __slots__ = ('path', 'video_id', 'rows', 'meta_rows', 'info',
                 'emitted', 'done', 'exhausted', 'failed', 'skipped',
                 'cached', 'out_root', 'finalized', 'segment', 'trace')

    def __init__(self, path: str, video_id: int = -1,
                 out_root: Optional[str] = None,
                 segment: Optional[tuple] = None,
                 trace=None) -> None:
        self.path = path
        self.video_id = video_id
        self.out_root = out_root
        # request-scoped trace context (obs/context.TraceContext, or
        # None for legacy CLI tasks): every span/instant this task's
        # work produces carries its trace_id/span_id, so one request's
        # timeline is a single filter over the merged export
        self.trace = trace
        # optional (start_s, end_s) time range (segment queries): the
        # windower decodes/extracts only the covered windows, outputs
        # are named via name_path, and the cache keys on the range.
        # Quantized to MILLISECONDS here — the one choke point — so the
        # frame filter, the output name, and the cache key all derive
        # from the same value: two sub-ms-different ranges must never
        # share a cache key while selecting different frames.
        if segment is not None:
            segment = (round(float(segment[0]), 3),
                       round(float(segment[1]), 3))
        self.segment = segment
        self.rows: Dict[str, List[np.ndarray]] = {}
        self.meta_rows: List = []
        self.info: Dict = {}
        self.emitted = 0
        self.done = 0
        self.exhausted = False
        self.failed = False
        self.skipped = False
        # skipped via a content-addressed cache hit (outputs materialized
        # from the cache rather than found on disk) — consumers that care
        # about the difference (serve per-video states, metrics) read it
        self.cached = False
        # terminal: finalize() ran (saved/failed/skipped, cache published,
        # on_video_done fired). The decode farm's dedupe reads it — a
        # parked duplicate waits for its twin's publish, never a
        # mid-flight state.
        self.finalized = False

    @property
    def name_path(self) -> str:
        """The path output files are NAMED after: the real path, or the
        segment-suffixed pseudo-path for a range extraction (so partial
        and full outputs never collide in one root). Decode and content
        hashing always use the real ``path``."""
        return segment_name(self.path, self.segment)


class FusedTask(VideoTask):
    """One video inside a fused multi-family run: the CARRIER the shared
    decode stream flows through, plus one per-family subtask.

    The carrier owns everything the decode side touches (``emitted`` /
    ``exhausted`` / ``failed`` / ``info`` — the farm and the in-process
    windower keep their bookkeeping unchanged on it); each family's
    scatter-back, fault isolation, and finalization state lives on its
    SUBTASK, a plain :class:`VideoTask` that the family's unchanged
    save/cache/finalize path consumes. A family's device-step fault
    fails only its subtask — the shared decode keeps feeding the
    healthy siblings; a DECODE fault fails the carrier, which fails
    every still-active subtask at finalize.

    ``active`` is the family subset still wanting this video after
    per-family admission (resume skips / cache hits drop out);
    ``farm_select`` mirrors it onto the farm task message so skipped
    families also drop out of the worker's transform fan-out.
    """

    __slots__ = ('subtasks', 'active', 'farm_select')

    def __init__(self, path: str, families: Iterable[str],
                 video_id: int = -1,
                 segment: Optional[tuple] = None, trace=None) -> None:
        super().__init__(path, video_id=video_id, segment=segment,
                         trace=trace)
        self.subtasks: Dict[str, VideoTask] = {
            fam: VideoTask(path, video_id=video_id, segment=segment,
                           trace=trace)
            for fam in families}
        self.active: List[str] = list(self.subtasks)
        self.farm_select = None


def packed_batches(windows: Iterable[tuple], batch: int,
                   max_pool_age_s: Optional[float] = None,
                   tracer: Tracer = NULL_TRACER,
                   family_of: Optional[Callable] = None,
                   family_batch: Optional[Dict] = None,
                   ) -> Iterator[Tuple[np.ndarray, list, int]]:
    """Group a cross-video ``(task, window, meta)`` stream into full
    fixed-size batches: ``(stacks, provenance, valid)`` where provenance is
    the per-slot ``(task, meta)`` list for the ``valid`` real slots.

    Windows pool per geometry (shape, dtype) so a mixed-resolution corpus
    still feeds fixed-shape compiled steps — a batch only ever mixes
    windows of identical geometry, and each geometry's pool holds at most
    ``batch - 1`` windows (memory stays bounded by the number of DISTINCT
    geometries in flight, not by corpus size).

    ``family_of`` (fused worklists) extends the pool key with the window
    meta's FAMILY, so a fused stream where two families share a geometry
    (resnet and clip both emit 224×224×3 uint8) still never mixes
    families in one batch — each family's batches must feed that
    family's own compiled program. ``family_batch`` (family → capacity)
    then lets each family's pools fill/pad at ITS packed batch size, so
    a fused run dispatches the exact per-family programs a sequential
    run compiles (no new program identities, no AOT-store misses). Tail pools flush padded
    (repeating the last window, masked via ``valid``) only once the whole
    worklist is drained — that final partial batch per geometry is the only
    padding the corpus pays, vs one per video in the per-video loop.

    A ``FLUSH`` item in the stream forces that tail flush early, for
    dynamic sources whose "worklist" has momentarily run dry: a serving
    queue must bound a lone request's latency by batch-padding now rather
    than waiting for future arrivals to fill the pool. Every ``FLUSH``
    (and every ``NUDGE``) is forwarded as the batchless drain marker
    ``(None, [], 0)`` after its pools flush, telling the consumer to
    materialize its in-flight output queue too — the async device loop
    defers D2H until the NEXT dispatch, and on an idle dynamic source
    that next dispatch may be hours away.

    ``max_pool_age_s`` (serving: ``serve_max_batch_wait_s``) additionally
    ages pools OUT-OF-BAND of the source: any pool whose oldest window
    has waited that long flushes padded as the next window — of ANY
    geometry — arrives. This is what bounds a lone odd-geometry request
    under CONTINUOUS traffic, where the upstream feed is never idle (and
    so never emits FLUSH) but other geometries' windows keep flowing.
    """
    import time as _time

    pools: Dict[tuple, list] = {}
    ages: Dict[tuple, float] = {}      # key → oldest pooled window's time

    def cap_of(key) -> int:
        # fused pools are keyed (family, shape, dtype) and fill at that
        # family's own packed batch size
        if family_batch is not None:
            return int(family_batch[key[0]])
        return batch

    def flush(key):
        pool = pools[key]
        pools[key] = []
        ages.pop(key, None)
        valid = len(pool)
        cap = cap_of(key)
        # the batch-assembly copy is the packer's own cost — timed as its
        # own 'pack' stage; the span attrs (videos in the batch) are
        # built ONLY when tracing is on, so the default hot loop stays
        # allocation-free. getattr, not t.path: unit tests drive the
        # packer with plain task tokens.
        attrs = ({'videos': sorted({str(getattr(t, 'path', t))
                                    for t, _, _ in pool}),
                  'valid': valid, 'capacity': cap}
                 if tracer.enabled else {})
        if tracer.enabled:
            # batch spans serve several requests at once: carry the SET
            # of trace ids so a per-request trace filter still finds the
            # shared pack/model/d2h work it rode on
            tids = trace_ids_of(t for t, _, _ in pool)
            if tids:
                attrs['trace_ids'] = tids
        with tracer.stage('pack', **attrs):
            wins = [w for _, w, _ in pool]
            while len(wins) < cap:
                wins.append(wins[-1])
            stacked = np.stack(wins)
        return stacked, [(t, m) for t, _, m in pool], valid

    for item in windows:
        if item is FLUSH:
            for key in list(pools):
                if pools[key]:
                    yield flush(key)
            # always follow with the batchless drain marker: the source
            # is momentarily idle, so the consumer must ALSO materialize
            # its in-flight output queue (async device loop) — without
            # this, a lone request's LAST dispatched batch would wait on
            # future traffic to push it through the deferred-D2H window
            yield None, [], 0
            continue
        if item is NUDGE:
            # batchless marker: lets the consumer sweep for zero-window
            # videos without waiting for a real batch (or stream end)
            yield None, [], 0
            continue
        task, window, meta = item
        window = np.asarray(window)
        key = (window.shape, window.dtype.str)
        if family_of is not None:
            key = (family_of(meta),) + key
        pool = pools.setdefault(key, [])
        if not pool:
            ages[key] = _time.monotonic()
        pool.append((task, window, meta))
        if len(pool) == cap_of(key):
            yield flush(key)
        if max_pool_age_s is not None:
            now = _time.monotonic()
            for k in list(pools):
                if pools[k] and now - ages[k] >= max_pool_age_s:
                    yield flush(k)
    for key in list(pools):
        if pools[key]:
            yield flush(key)


def _admit_task(ex, task: VideoTask) -> bool:
    """The per-video admission gate, shared by the single-family and
    fused packed drivers (fused runs it once per (family, video) against
    that family's extractor — resume skips and cache hits stay
    per-family). False means the video is terminal for ``ex`` without
    decoding; ``task.skipped``/``task.cached`` say why."""
    # ephemeral tasks (ingress live sessions) have no file behind
    # them: nothing to resume, nothing to content-hash — always run
    if getattr(task, 'ephemeral', False):
        return True
    # The resume check runs here — lazily, as the decode side reaches
    # each video — NOT as an up-front scan: is_already_exist loads
    # every output file, and an eager pass over a mostly-done 20K
    # worklist would block for minutes before the first batch packs.
    # Amortized across the run it costs what the per-video loop paid.
    # (The farm's dispatcher keeps the same property via its bounded
    # assignment runahead.)
    # the output_path kwarg is passed only when a task carries a
    # per-request root: hooks monkeypatched/overridden with the
    # classic (self, video_path) signature keep working for CLI runs.
    # name_path (== path unless the task carries a segment range)
    # keys both resume and the cache materialization target, so a
    # range extraction never reuses — or clobbers — full outputs.
    name = task.name_path
    exists = (ex.is_already_exist(name, output_path=task.out_root)
              if task.out_root is not None
              else ex.is_already_exist(name))
    if exists:
        task.skipped = True
        return False
    # content-addressed cache: a hit materializes this video's outputs
    # right here and drops it from batch planning entirely — it never
    # decodes, never occupies batch slots, and finalizes through the
    # same sweep/on_video_done path as a resume skip
    if getattr(ex, 'cache', None) is not None and \
            ex.cache_fetch(task.path, output_path=task.out_root,
                           segment=task.segment, name_path=name):
        task.skipped = True
        task.cached = True
        return False
    return True


def _finalize_task(ex, t: VideoTask, recorder=None, manifest=None,
                   on_video_done: Optional[Callable] = None) -> None:
    """Finalize one (family, video): save/publish (unless skipped or
    failed), free its rows, stamp the outcome on the recorder/manifest,
    fire ``on_video_done``. Shared by the single-family driver's sweep
    and the fused driver's per-family fan-out — the fused path MUST go
    through the identical save/cache code for its byte-identity
    contract."""
    from video_features_tpu.extract.base import log_extraction_error
    try:
        if not (t.failed or t.skipped
                or getattr(t, 'stream_only', False)):
            # stream_only (live sessions) already delivered every
            # window through on_window — nothing to save or publish
            feats_dict = ex._maybe_concat_streams(ex.packed_result(t))
            with ex.tracer.stage('save', video=str(t.path),
                                 request_id=_request_id(t),
                                 **trace_attrs(t)):
                if t.out_root is not None:
                    ex.action_on_extraction(feats_dict, t.name_path,
                                            output_path=t.out_root)
                else:
                    ex.action_on_extraction(feats_dict, t.name_path)
            if getattr(ex, 'cache', None) is not None:
                with ex.tracer.stage('cache_publish',
                                     video=str(t.path)):
                    ex.cache_publish(t.path, output_path=t.out_root,
                                     segment=t.segment,
                                     name_path=t.name_path)
    except KeyboardInterrupt:
        raise
    except Exception:
        t.failed = True           # a failed save IS a failed video
        log_extraction_error(t.path, request_id=_request_id(t),
                             stage='save')
    finally:
        t.rows = {}               # free feature memory as we go
        t.finalized = True        # the farm's dedupe unparks twins now
        from video_features_tpu.utils.output import ACTION_TO_EXT
        outcome = ('failed' if t.failed else 'cached' if t.cached
                   else 'skipped' if t.skipped
                   else 'saved' if ex.on_extraction in ACTION_TO_EXT
                   else 'printed')
        if recorder is not None:
            recorder.instant('video_done', video=str(t.path),
                             outcome=outcome,
                             request_id=_request_id(t),
                             **trace_attrs(t))
        if manifest is not None:
            manifest.video_done(t.path, outcome)
        if on_video_done is not None:
            on_video_done(t)


def run_packed(ex, video_paths: Iterable,
               batch_size: Optional[int] = None,
               decode_ahead: int = 2,
               on_video_done: Optional[Callable] = None,
               max_pool_age_s: Optional[float] = None,
               inflight: Optional[int] = None,
               decode_workers: Optional[int] = None) -> None:
    """Drive one extractor over the whole worklist, batch-major.

    ``video_paths`` yields ``str`` paths, pre-built :class:`VideoTask`
    objects (dynamic sources attach request state / ``out_root``), or the
    ``FLUSH`` sentinel; it is consumed LAZILY on the decode thread and may
    block — a serving queue feeds the packer exactly like a static
    worklist, the stream simply ends when the source drains.
    ``on_video_done(task)`` (if given) fires after each video finalizes —
    saved, skipped, failed, or empty — which is how the serving layer maps
    scattered videos back to request completions.

    Preserves every externally observable per-video contract:

      * resume — ``is_already_exist`` is checked as the decode side
        reaches each video (same skip message, amortized like the
        per-video loop — never an up-front O(corpus) scan) and re-checked
        by ``action_on_extraction`` right before writing, so concurrent
        workers still collide benignly;
      * outputs — identical filenames and array contents flow through the
        same ``_maybe_concat_streams`` + ``action_on_extraction`` path;
      * fault isolation — a video that fails to decode, compute, or save
        prints the same error and the worklist continues; windows it
        contributed to shared batches are computed but never saved, and a
        device-step failure (e.g. a geometry that won't compile) fails
        only the videos in that batch — one bad video cannot poison the
        batch it shares, nor abort the worklist.

    ``decode_ahead`` bounds the cross-video decode lookahead at
    ``decode_ahead × batch`` windows (see ``io.video.
    prefetch_across_videos``).

    ``batch_size`` (default: the extractor's ``packed_batch_size``) is
    the PER-DEVICE capacity. With ``mesh_devices > 1`` the loop is
    mesh-sharded: batches plan at ``capacity × ndev``, ``put_input``
    shards each stacked batch over the data axis of the extractor's
    mesh (params replicated per chip — ``_ensure_packed_mesh``), and
    every device runs the family's unchanged packed program at its
    single-chip batch shape, so outputs are byte-identical at any
    device count. Uneven tails pad (and mask at scatter-back) exactly
    like single-device tails — a lone window never stalls the batch —
    and fault isolation is untouched: a poisoned window fails its
    video, not its shard. The ``model``/``d2h`` spans carry
    ``mesh_devices`` + per-shard valid counts, occupancy records both
    the global aggregate and each device's share, and the run manifest
    records the mesh shape.

    ``inflight`` (default: the extractor's ``inflight`` attribute, 2) is
    the OUTPUT-side pipelining depth: ``packed_step`` only dispatches
    (it returns device arrays), and the loop keeps up to ``inflight``
    dispatched batches queued before materializing the oldest one's
    results with ``ex.fetch_outputs`` — so the D2H readback, row
    scatter, ``sweep()`` finalization, and output writes of batch k-1
    all overlap the device computing batch k. ``inflight=1`` is exactly
    the old synchronous loop (dispatch, then immediately fetch), and
    outputs are byte-identical at any depth. Cost: each extra unit keeps
    one more output batch (B × feat_dim per stream) resident on device.
    Fault isolation covers BOTH failure sites — a dispatch-time error
    (e.g. a geometry that won't compile) and a sync-time error (an
    asynchronously raised execution fault surfacing in ``fetch_outputs``)
    each doom exactly the videos of the batch that produced them.

    ``decode_workers`` (default: the extractor's ``decode_workers``
    attribute) selects the INPUT side's parallelism: ``1`` is the
    in-process cross-video windower exactly as before; ``>1`` routes
    decode through the multi-process decode farm (``farm/``) — N worker
    processes running the extractor's published decode recipe, feeding
    this scheduler over shared-memory rings with the same stream
    contract, per-video fault isolation, and byte-identical outputs.
    Falls back to in-process decode (with a structured warning) when the
    extractor has no farm recipe or the host can't spawn workers.
    """
    from video_features_tpu.extract.streaming import (
        stream_windows_across_videos, transfer_batches,
    )
    from video_features_tpu.io.video import prefetch_across_videos

    ex._packed_setup()
    # mesh-sharded execution (mesh_devices > 1): the device loop plans
    # batches at capacity × ndev, put_input shards each stacked batch
    # over the data axis of the extractor's mesh (params replicated per
    # chip), and the in-flight queue / scatter-back below run UNCHANGED —
    # fetch_outputs gathers the sharded output, each row scatters to its
    # video, and a poisoned window still fails only its video. Per-shard
    # capacity equals the single-chip batch, so every device runs the
    # exact program the family was tuned for and outputs stay
    # byte-identical at any device count.
    ndev = ex._ensure_packed_mesh()
    capacity = int(batch_size or ex.packed_batch_size())
    if ndev > 1:
        from video_features_tpu.parallel.mesh import plan_device_batch
        batch = plan_device_batch(capacity, ex._mesh)
    else:
        batch = capacity

    def shard_valids(valid: int) -> list:
        """Per-device valid-slot counts for a ``valid``-row global batch:
        shard i holds rows [i·capacity, (i+1)·capacity) — uneven tails
        leave later shards partially (or fully) padded, masked at
        scatter-back like any other padding."""
        return [max(0, min(valid - i * capacity, capacity))
                for i in range(ndev)]

    # per-device telemetry labels ('d<jax device id>'), data-axis order
    dev_labels = ([f'd{d.id}' for d in ex._mesh.devices.flat]
                  if ndev > 1 else [])

    # which precision lane computed every model/d2h span of this run
    # (ops/precision.py): a trace or crash bundle must say which lane
    # produced it — an fp32-vs-bf16 perf or drift question is otherwise
    # unanswerable post-hoc
    compute_dtype = str(getattr(ex, 'compute_dtype', 'float32'))

    def mesh_attrs(valid: int) -> Dict:
        """Extra span attrs for mesh-sharded model/d2h stages: the mesh
        width and each shard's valid-slot count (empty single-device),
        plus the compute_dtype lane on every packed run."""
        if not ex.tracer.enabled:
            return {}
        attrs: Dict = {'compute_dtype': compute_dtype}
        if ndev > 1:
            attrs.update(mesh_devices=ndev,
                         shard_valid=shard_valids(valid))
        return attrs

    def record_occupancy(name: str, valid: int) -> None:
        """Aggregate occupancy at the GLOBAL capacity plus — on a mesh —
        one record per device shard at the per-device capacity; the two
        views never double-count (tracing.add_occupancy)."""
        ex.tracer.add_occupancy(name, valid, batch)
        if ndev > 1:
            for label, v in zip(dev_labels, shard_valids(valid)):
                ex.tracer.add_occupancy(name, v, capacity, device=label)

    recorder = getattr(ex.tracer, 'recorder', None)
    manifest = getattr(ex, 'manifest', None)
    # executable identity → (shape, dtype) seen on the device loop;
    # cost-analyzed after the run so telemetry never stalls a batch
    costed: Dict[str, tuple] = {}

    # open_q doubles as the lazy task registry: the decode thread appends
    # each task as the source yields it (list.append is atomic; only the
    # consumer thread deletes), so a blocking dynamic source needs no
    # up-front worklist materialization.
    open_q: List[VideoTask] = []
    n_started = [0]

    # the extractor's run-level trace context (CLI runs with trace_out:
    # configure_obs mints one — "a CLI run is one request"): bare paths
    # wrap into tasks carrying a child span under it, so the packed
    # path's spans are trace-filterable exactly like serve requests'.
    # Pre-built tasks (serve) already carry their request's context.
    run_ctx = getattr(ex, 'trace_ctx', None)

    def task_stream() -> Iterator:
        for item in video_paths:
            if item is FLUSH:
                yield FLUSH
                continue
            task = (item if isinstance(item, VideoTask)
                    else VideoTask(item,
                                   trace=(run_ctx.child()
                                          if run_ctx is not None
                                          else None)))
            task.video_id = n_started[0]
            n_started[0] += 1
            open_q.append(task)
            if recorder is not None:
                recorder.instant('video_start', video=str(task.path),
                                 request_id=_request_id(task),
                                 **trace_attrs(task))
            yield task

    def admit(task: VideoTask) -> bool:
        return _admit_task(ex, task)

    def open_windows(task: VideoTask):
        if not admit(task):
            return iter(())
        # live tasks (ingress live sessions) carry their own window
        # source — frames arriving over the network, windowed to the
        # extractor's geometry — instead of decoding task.path
        override = getattr(task, 'windows_override', None)
        if override is not None:
            return override(ex)
        return ex.packed_windows(task)

    # flush each video as soon as its last window's features land. NOT
    # strictly in worklist order: a video whose geometry pool can't fill
    # (e.g. the lone odd-resolution clip in a mixed corpus — its tail
    # windows sit pooled until the final drain) must not hold up every
    # video behind it, or their accumulated rows pin O(corpus) host RAM
    # and a crash loses outputs that were long since computed. The scan
    # stops at the first video the decode side hasn't reached (videos
    # start strictly in worklist order), so each sweep touches only the
    # small in-flight window, not the whole worklist.

    def finalize(t: VideoTask) -> None:
        _finalize_task(ex, t, recorder=recorder, manifest=manifest,
                       on_video_done=on_video_done)

    def sweep(final: bool = False) -> None:
        i = 0
        while i < len(open_q):
            t = open_q[i]
            if not t.exhausted and t.emitted == 0:
                break                 # decode hasn't reached this video yet
            if t.exhausted and t.done >= t.emitted:
                del open_q[i]
                finalize(t)
            else:
                i += 1
        if final and open_q:
            # the stream is fully drained; every task must be ready
            t = open_q[0]
            raise AssertionError(
                f'packed scheduler lost windows for {t.path}: '
                f'{t.done}/{t.emitted} scattered, exhausted={t.exhausted}')

    # -- input side: in-process windower, or the decode farm ----------------
    # decode_workers > 1 routes the decode+preprocess work through N
    # worker PROCESSES (farm/) feeding this scheduler over shared-memory
    # rings — same stream contract ((task, window, meta) + FLUSH/NUDGE,
    # per-video fault isolation, task accounting), so everything below
    # this point is identical on both paths and outputs stay
    # byte-identical at any worker count.
    n_decode = max(int(decode_workers if decode_workers is not None
                       else getattr(ex, 'decode_workers', 1) or 1), 1)
    farm = None
    if n_decode > 1:
        from video_features_tpu.farm import farm_available
        recipe = None
        recipe_err: Optional[BaseException] = None
        try:
            recipe = ex.farm_recipe()
        # vft-lint: ok=swallowed-exception — stored, not swallowed: the
        # structured recipe-failure warning below reports recipe_err
        except Exception as e:
            recipe_err = e                     # a BROKEN recipe, not a
            recipe = None                      # family without one
        if recipe is None or not farm_available():
            import logging as _logging

            from video_features_tpu.obs.events import event
            event(_logging.WARNING,
                  f'decode_workers={n_decode} requested but '
                  + (f'building its decode recipe failed '
                     f'({type(recipe_err).__name__}: {recipe_err})'
                     if recipe_err is not None else
                     'this extractor publishes no decode recipe'
                     if recipe is None else
                     'the host cannot spawn shared-memory workers')
                  + ' — running in-process decode', subsystem='farm')
        else:
            from video_features_tpu.farm import DecodeFarm, FarmUnavailable
            ring_mb = int(getattr(ex, 'decode_farm_ring_mb', 64) or 64)
            farm = DecodeFarm(
                recipe, workers=n_decode,
                ring_bytes=ring_mb * (1 << 20), tracer=ex.tracer,
                # post-mortem target (obs/blackbox.py): a dead decode
                # worker dumps a bundle alongside the respawn
                blackbox=getattr(ex, 'blackbox', None),
                # stall-watchdog feed (obs/watchdog.py): per-worker
                # assignment backlog, mirrored on the supervise tick
                pending_cb=getattr(ex, 'watchdog_pending', None),
                cache_key_fn=(ex._video_cache_key
                              if getattr(ex, 'cache', None) is not None
                              else None),
                # live tasks (windows_override) never ship to a worker
                # process — their frames arrive over the network in the
                # parent; the farm runs them on a feeder thread instead
                live_open=lambda task: task.windows_override(ex))
            # start eagerly: a RUNTIME start failure (SHM creation on a
            # full /dev/shm, a spawn refused by the container) must
            # degrade to in-process decode like every other farm
            # unavailability, not abort the whole worklist run
            try:
                farm.start()
            except FarmUnavailable as e:
                import logging as _logging

                from video_features_tpu.obs.events import event
                event(_logging.WARNING,
                      f'decode_workers={n_decode} requested but {e} '
                      '— running in-process decode', subsystem='farm')
                farm = None
            else:
                # live handle for the serve metrics surface (vft_farm_*);
                # stats stay readable after the run ends
                ex._farm = farm

    if farm is not None:
        source = farm.stream(task_stream(), admit)
    else:
        source = stream_windows_across_videos(task_stream(), open_windows)

    def timed_source():
        # decode (and host preprocessing) runs on the prefetch producer
        # thread, ahead of the device across video boundaries; timed here
        # (inside the prefetch) so decode cost lands on the thread that
        # spends it. A dynamic source (serve) also BLOCKS in next() while
        # its request queue is idle — those spans surface as FLUSH items
        # and are attributed to a separate ``queue_idle`` stage, not
        # laundered into decode time.
        import time as _time
        it = iter(source)
        while True:
            t0 = _time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            if item is FLUSH:
                ex.tracer.add('queue_idle', _time.perf_counter() - t0,
                              t0=t0)
            elif item is NUDGE:
                ex.tracer.add('decode+preprocess',
                              _time.perf_counter() - t0, t0=t0)
            else:
                # span provenance: the video (and serve request + trace)
                # this decode slice worked for
                ex.tracer.add('decode+preprocess',
                              _time.perf_counter() - t0, t0=t0,
                              video=str(item[0].path),
                              request_id=_request_id(item[0]),
                              **trace_attrs(item[0]))
            yield item

    # the farm traces per-worker 'decode' spans from the workers' own
    # timings; the consumer-side wrapper would only launder queue waits
    # into decode time, so it stays on the in-process path
    timed = timed_source() if ex.tracer.enabled and farm is None else source
    ahead = prefetch_across_videos(timed, decode_ahead * batch)

    # the in-flight queue: dispatched-but-unmaterialized batches, oldest
    # first. ``depth=1`` degenerates to the old synchronous loop (every
    # dispatch is immediately followed by its fetch); deeper queues let
    # the D2H readback + scatter + save of batch k-1 overlap the device
    # computing batch k. ``ex._inflight_now`` mirrors the live depth for
    # the serve metrics gauge (vft_inflight_batches) — a plain attribute
    # store, no locking needed for a monitoring read.
    from collections import deque
    depth = max(int(inflight if inflight is not None
                    else getattr(ex, 'inflight', 1) or 1), 1)
    # (out_dev, prov, valid, batch_videos, batch_traces)
    pending: 'deque' = deque()
    ex._inflight_now = 0

    def batch_trace_ids(prov) -> Optional[list]:
        """Distinct trace ids riding this batch (tracing on only) — the
        model/d2h spans carry them so a per-request trace filter finds
        the shared device work too."""
        if not ex.tracer.enabled:
            return None
        return trace_ids_of(t for t, _ in prov) or None

    def doom_batch(prov, batch_videos, valid, stage):
        # fault isolation (shared by the dispatch and sync sites): a
        # failing batch fails exactly the videos it carries (the
        # per-video loop would likewise lose only them) and the worklist
        # continues; their accounting still advances so the sweep never
        # stalls
        from video_features_tpu.obs.events import log_batch_error
        log_batch_error(batch_videos if batch_videos is not None
                        else sorted({str(t.path) for t, _ in prov}),
                        valid, batch, stage=stage)
        for task, _ in prov:
            task.failed = True
            task.done += 1

    def sync_oldest() -> None:
        """Materialize the OLDEST in-flight batch: the deferred D2H (its
        own ``d2h`` stage — readback must not launder into compute time)
        plus row scatter; asynchronously raised execution faults surface
        here and doom only this batch's videos."""
        out_dev, prov, valid, batch_videos, batch_traces = \
            pending.popleft()
        ex._inflight_now = len(pending)
        try:
            with ex.tracer.stage(
                    'd2h', videos=batch_videos, valid=valid,
                    capacity=batch,
                    **({'trace_ids': batch_traces} if batch_traces
                       else {}),
                    **mesh_attrs(valid)):
                out = ex.fetch_outputs(out_dev)
        except KeyboardInterrupt:
            raise
        except Exception:
            doom_batch(prov, batch_videos, valid, 'd2h')
            sweep()
            return
        record_occupancy('d2h', valid)
        for i, (task, meta) in enumerate(prov):
            task.done += 1
            if task.failed:       # already doomed: don't grow its rows
                continue
            on_window = getattr(task, 'on_window', None)
            if on_window is not None:
                # per-window streaming (live sessions): deliver this
                # row NOW instead of waiting for the video to finalize.
                # A delivery failure (client hung up) fails the task —
                # which also tells the decode side to stop feeding it.
                try:
                    on_window({key: arr[i] for key, arr in out.items()},
                              meta)
                except Exception:
                    task.failed = True
                    # a one-line event, not log_extraction_error: the
                    # vanished client is the CAUSE, the task failure is
                    # the effect — but it must not be silent (a leaked
                    # quota unit / session would be invisible otherwise)
                    event(_logging.WARNING,
                          'per-window delivery failed; failing the '
                          'live task', exc_info=True,
                          video=str(task.path), stage='d2h')
                    continue
            if getattr(task, 'stream_only', False):
                continue          # don't pin a live session's rows in RAM
            for key, arr in out.items():
                task.rows.setdefault(key, []).append(arr[i])
            task.meta_rows.append(meta)
        sweep()

    with ex.precision_scope():
        # batch assembly + H2D of batch k+1 overlap the device running k
        for dev, _, prov, valid in transfer_batches(
                packed_batches(ahead, batch, max_pool_age_s=max_pool_age_s,
                               tracer=ex.tracer),
                ex.put_input, tracer=ex.tracer):
            if dev is None:
                # batchless drain marker (NUDGE / post-FLUSH): the source
                # is idle or a video finished without windows — finalize
                # everything finishable NOW. That means materializing the
                # whole in-flight queue first (a dynamic source may not
                # dispatch another batch for hours, and a deferred batch
                # must not hold its requests' completions hostage).
                while pending:
                    sync_oldest()
                sweep()
                continue
            # span provenance only when tracing is on (hot-loop hygiene);
            # the error path below rebuilds the list lazily if needed
            batch_videos = (sorted({str(t.path) for t, _ in prov})
                            if ex.tracer.enabled else None)
            batch_traces = batch_trace_ids(prov)
            try:
                # 'model' times dispatch + any compute the backend runs
                # synchronously; the wait-for-results tail lands on the
                # 'd2h' stage at the sync point (their shares sum to the
                # old all-in 'model' share)
                with ex.tracer.stage(
                        'model', videos=batch_videos, valid=valid,
                        capacity=batch,
                        **({'trace_ids': batch_traces} if batch_traces
                           else {}),
                        **mesh_attrs(valid)):
                    out = ex.packed_step(dev)
            except KeyboardInterrupt:
                raise
            except Exception:
                # dispatch-time fault (e.g. a geometry that won't
                # compile/fit): in-flight predecessors are unaffected
                doom_batch(prov, batch_videos, valid, 'model')
                sweep()
                continue
            record_occupancy('model', valid)
            if manifest is not None:
                # record each executable identity's geometry (the unit
                # XLA compiles per) — shape+dtype only; the expensive
                # cost-analysis lowering runs AFTER the worklist, off
                # the device loop's critical path
                shape = getattr(dev, 'shape', None)
                if shape is not None:
                    # the identity names the LANE too when it isn't the
                    # default: fp32 and bf16 entries lower different
                    # programs at the same input geometry (the packed
                    # batch itself is usually uint8 on both lanes)
                    lane = ('' if compute_dtype == 'float32'
                            else f':{compute_dtype}')
                    identity = (f'{getattr(ex, "feature_type", "?")}:'
                                f'{tuple(shape)}:'
                                f'{getattr(dev, "dtype", "")}{lane}')
                    if identity not in costed:
                        costed[identity] = (tuple(shape),
                                            getattr(dev, 'dtype', None))
            pending.append((out, prov, valid, batch_videos,
                            batch_traces))
            ex._inflight_now = len(pending)
            while len(pending) >= depth:
                sync_oldest()
        while pending:            # stream drained: materialize the tail
            sync_oldest()
    ex._inflight_now = 0
    sweep(final=True)

    if manifest is not None and costed:
        # deferred XLA cost analysis: lower the step at each recorded
        # geometry (abstract shapes — no data needed) now that the
        # worklist is done; with the persistent compilation cache on
        # this is a cache read, and either way it is off the hot path
        import jax
        for identity, (shape, dtype) in costed.items():
            # every executable record names its lane, so the manifest's
            # xla_cost_analysis section says which precision produced
            # the FLOPs/bytes it reports
            info: Dict = {'batch': batch, 'compute_dtype': compute_dtype}
            cost = ex.executable_cost(jax.ShapeDtypeStruct(shape, dtype)) \
                if dtype is not None else None
            if cost:
                info.update(cost)
            manifest.note_executable(identity, info)

    if manifest is not None and ndev > 1:
        # the run manifest names the mesh that produced these numbers:
        # device count, (data, time) shape, and the per-device labels the
        # stage table / metrics key their occupancy records on
        manifest.note_mesh({
            'mesh_devices': ndev,
            'shape': {str(k): int(v) for k, v in ex._mesh.shape.items()},
            'devices': dev_labels,
            'capacity_per_device': capacity,
            'global_batch': batch,
            # which precision lane this mesh's programs computed in —
            # a bf16 entry is a different compiled program at the same
            # width, and the manifest must say which one ran
            'compute_dtype': compute_dtype})

    if farm is not None and manifest is not None:
        # farm config + lifetime stats land in the run manifest (the
        # 'farm' section) so a farm-backed BENCH/run record names the
        # decode parallelism that produced it
        manifest.note_farm({'decode_workers': farm.n_workers,
                            'ring_bytes_per_worker': farm.ring_bytes,
                            'stats': farm.stats()})

    if ex.tracer.enabled and ex.tracer.report():
        if manifest is not None:
            # fold BEFORE the reset: the manifest keeps the run aggregate
            manifest.fold_stages(ex.tracer.report())
        if getattr(ex, 'profile', True):
            mesh_note = (f' = {capacity} x {ndev} devices'
                         if ndev > 1 else '')
            # stderr: the stage table is a diagnostic, and with
            # on_extraction=print stdout carries features
            print(f'--- stage timing: packed worklist ({n_started[0]} '
                  f'videos, batch {batch}{mesh_note})', file=sys.stderr)
            print(ex.tracer.summary(), file=sys.stderr)
        ex.tracer.reset()


# -- fused multi-family worklists: decode once, extract many ----------------


def build_fused_recipe(exs: Dict):
    """One :class:`farm.recipes.FusedRecipe` for a family→extractor map
    whose ``fused_decode_signature()`` values all match: the shared
    decode geometry comes from the lead (first) family — the signature
    equality the caller established means every family would have built
    the identical loader — and the per-family branch transforms are each
    family's own published ``host_transform_spec()``."""
    from video_features_tpu.farm.recipes import FusedRecipe
    lead = next(iter(exs.values()))
    return FusedRecipe(
        batch_size=lead.batch_size, fps=lead.extraction_fps,
        total=lead.extraction_total, tmp_path=lead.tmp_path,
        keep_tmp=lead.keep_tmp_files, backend=lead.decode_backend,
        transforms={fam: ex.host_transform_spec()
                    for fam, ex in exs.items()})


def run_packed_fused(exs: Dict, video_paths: Iterable,
                     batch_size: Optional[int] = None,
                     decode_ahead: int = 2,
                     on_video_done: Optional[Callable] = None,
                     max_pool_age_s: Optional[float] = None,
                     inflight: Optional[int] = None,
                     decode_workers: Optional[int] = None) -> None:
    """Drive N same-decode-signature extractors over ONE worklist with
    ONE decode pass per video.

    ``exs`` maps family name → warm extractor; every extractor must
    publish the same ``fused_decode_signature()`` (the caller groups by
    it — ``cli.py``). Per video, the shared raw frame stream is decoded
    once and branched through each family's named host transform
    (``FusedRecipe``), each window arrives tagged ``meta=(family,
    t_ms)``, and the packer pools per ``(family, geometry)`` at that
    family's own packed batch size — so the device sees the exact
    per-family programs a sequential run compiles (no new program
    identities, no AOT-store misses) and every family's outputs are
    byte-identical to its solo run.

    Scheduling state is a :class:`FusedTask` CARRIER per video (the
    decode side's bookkeeping object) plus per-family subtasks that own
    scatter-back, fault isolation, and finalization:

      * admission runs per (family, video) through the shared
        ``_admit_task`` gate — resume skips and cache hits stay
        per-family, and a video every family skips never decodes;
        families that drop out at admission are excluded from the
        decode fan-out (``farm_select`` on the farm task message, the
        ``select`` arg in-process), so a mostly-cached family costs no
        transform work either;
      * the video's content hash is computed ONCE (``cache.key``'s
        stat-memoized ``hash_file``) and reused by every family's cache
        key — the fused run's cache keys are identical to sequential
        runs';
      * a family's device-step fault fails only that family's subtask —
        the shared decode keeps feeding the healthy siblings; a DECODE
        fault fails the carrier, and with it every still-active
        subtask;
      * finalization fans each subtask through the shared
        ``_finalize_task`` (identical save/publish code), then fires
        ``on_video_done(carrier)`` once per video.

    ``decode_workers > 1`` ships the fused recipe to the decode farm
    unchanged — one worker decode per video, N tagged window streams
    back over the ring. The D2H side keeps a per-family in-flight queue
    at each family's ``inflight`` depth. Simplification vs
    ``run_packed``: H2D runs inline per batch (its own ``h2d`` stage)
    rather than through ``transfer_batches`` — with N families
    interleaving on one device loop there is no single "next batch" to
    overlap against.
    """
    from video_features_tpu.extract.streaming import (
        stream_windows_across_videos,
    )
    from video_features_tpu.io.video import prefetch_across_videos

    if not exs:
        raise ValueError('run_packed_fused needs at least one family')
    sigs = {fam: ex.fused_decode_signature() for fam, ex in exs.items()}
    if None in sigs.values() or len(set(sigs.values())) != 1:
        raise ValueError(
            f'families cannot share one decode pass — fused decode '
            f'signatures differ or are unfusable: {sigs}')

    fams = list(exs)
    lead = exs[fams[0]]

    # per-family device setup + batch plan: each family keeps ITS packed
    # batch size (and mesh plan), so fused batches feed the family's own
    # compiled programs
    fam_batch: Dict[str, int] = {}
    for fam, ex in exs.items():
        ex._packed_setup()
        ndev = ex._ensure_packed_mesh()
        capacity = int(batch_size or ex.packed_batch_size())
        if ndev > 1:
            from video_features_tpu.parallel.mesh import plan_device_batch
            fam_batch[fam] = plan_device_batch(capacity, ex._mesh)
        else:
            fam_batch[fam] = capacity
        ex._inflight_now = 0
    max_batch = max(fam_batch.values())

    recorders = {fam: getattr(ex.tracer, 'recorder', None)
                 for fam, ex in exs.items()}
    manifests = {fam: getattr(ex, 'manifest', None)
                 for fam, ex in exs.items()}
    lead_recorder = recorders[fams[0]]
    run_ctx = getattr(lead, 'trace_ctx', None)

    open_q: List[FusedTask] = []
    n_started = [0]

    def task_stream() -> Iterator:
        for item in video_paths:
            if item is FLUSH:
                yield FLUSH
                continue
            c = (item if isinstance(item, FusedTask)
                 else FusedTask(item, fams,
                                trace=(run_ctx.child()
                                       if run_ctx is not None
                                       else None)))
            c.video_id = n_started[0]
            n_started[0] += 1
            open_q.append(c)
            if lead_recorder is not None:
                lead_recorder.instant('video_start', video=str(c.path),
                                      **trace_attrs(c))
            yield c

    def admit_fused(c: FusedTask) -> bool:
        """Per-family admission over the shared carrier: families whose
        subtask resolves at admit (resume skip / cache hit) drop out of
        the decode fan-out; the video decodes only if someone still
        wants it. Emits the ``decode_pass`` instant exactly once per
        video that will decode — the observable the fused amortization
        guard (tests) asserts on."""
        active = []
        for fam in c.subtasks:
            sub = c.subtasks[fam]
            if _admit_task(exs[fam], sub):
                active.append(fam)
            else:
                sub.exhausted = True   # terminal now; finalized with the
                #                        carrier so outcomes record once
        c.active = active
        c.farm_select = (tuple(active)
                         if active and len(active) < len(c.subtasks)
                         else None)
        if active and lead_recorder is not None:
            lead_recorder.instant('decode_pass', video=str(c.path),
                                  families=list(active),
                                  **trace_attrs(c))
        return bool(active)

    # -- input side: one shared decode, farm or in-process ------------------
    n_decode = max(int(decode_workers if decode_workers is not None
                       else getattr(lead, 'decode_workers', 1) or 1), 1)
    farm = None
    if n_decode > 1:
        from video_features_tpu.farm import farm_available
        if farm_available():
            from video_features_tpu.farm import DecodeFarm, FarmUnavailable
            ring_mb = int(getattr(lead, 'decode_farm_ring_mb', 64) or 64)
            farm = DecodeFarm(
                build_fused_recipe(exs), workers=n_decode,
                ring_bytes=ring_mb * (1 << 20), tracer=lead.tracer,
                blackbox=getattr(lead, 'blackbox', None),
                pending_cb=getattr(lead, 'watchdog_pending', None),
                # content-keyed dedupe stays off: per-family cache keys
                # diverge, so a carrier-level key could merge videos one
                # family still needs separately
                cache_key_fn=None)
            try:
                farm.start()
            except FarmUnavailable as e:
                event(_logging.WARNING,
                      f'decode_workers={n_decode} requested but {e} '
                      '— running in-process decode', subsystem='farm')
                farm = None
            else:
                lead._farm = farm
        else:
            event(_logging.WARNING,
                  f'decode_workers={n_decode} requested but the host '
                  'cannot spawn shared-memory workers — running '
                  'in-process decode', subsystem='farm')

    if farm is not None:
        source = farm.stream(task_stream(), admit_fused)
    else:
        recipe = build_fused_recipe(exs)

        def fused_open_windows(c: FusedTask):
            if not admit_fused(c):
                return iter(())
            kw = {}
            if c.segment is not None:
                kw['segment'] = c.segment
            if c.farm_select is not None:
                kw['select'] = c.farm_select
            info, windows = recipe.open(c.path, **kw)
            c.info.update(info)
            return windows

        source = stream_windows_across_videos(task_stream(),
                                              fused_open_windows)

    def timed_source():
        # in-process decode+branch cost, attributed per family window on
        # the lead tracer (the farm path traces in-worker spans itself)
        import time as _time
        it = iter(source)
        while True:
            t0 = _time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            dt = _time.perf_counter() - t0
            if item is FLUSH:
                lead.tracer.add('queue_idle', dt, t0=t0)
            elif item is NUDGE:
                lead.tracer.add('decode+preprocess', dt, t0=t0)
            else:
                lead.tracer.add('decode+preprocess', dt, t0=t0,
                                video=str(item[0].path),
                                family=item[2][0],
                                **trace_attrs(item[0]))
            yield item

    timed = (timed_source() if lead.tracer.enabled and farm is None
             else source)

    def counted(src):
        # PRODUCER-side per-family emit accounting: runs between the
        # windower (which counts the carrier) and the prefetch buffer,
        # so by the time the consumer can observe ``carrier.exhausted``
        # every subtask's ``emitted`` is final — the sweep's readiness
        # check (done >= emitted per active family) cannot fire early
        for item in src:
            if item is not FLUSH and item is not NUDGE:
                sub = item[0].subtasks.get(item[2][0])
                if sub is not None:
                    sub.emitted += 1
            yield item

    ahead = prefetch_across_videos(counted(timed), decode_ahead * max_batch)

    from collections import deque
    depth = {fam: max(int(inflight if inflight is not None
                          else getattr(ex, 'inflight', 1) or 1), 1)
             for fam, ex in exs.items()}
    pending: Dict[str, deque] = {fam: deque() for fam in fams}
    costed: Dict[str, Dict[str, tuple]] = {fam: {} for fam in fams}

    def finalize_carrier(c: FusedTask) -> None:
        for fam, sub in c.subtasks.items():
            for k, v in c.info.items():
                sub.info.setdefault(k, v)
            if c.failed and not sub.skipped:
                sub.failed = True    # decode fault fails every family
            sub.exhausted = True
            _finalize_task(exs[fam], sub, recorder=recorders[fam],
                           manifest=manifests[fam])
        c.rows = {}
        c.finalized = True
        if on_video_done is not None:
            on_video_done(c)

    def sweep(final: bool = False) -> None:
        i = 0
        while i < len(open_q):
            c = open_q[i]
            if not c.exhausted and c.emitted == 0:
                break             # decode hasn't reached this video yet
            if c.exhausted and all(c.subtasks[f].done
                                   >= c.subtasks[f].emitted
                                   for f in c.active):
                del open_q[i]
                finalize_carrier(c)
            else:
                i += 1
        if final and open_q:
            c = open_q[0]
            counts = {f: (c.subtasks[f].done, c.subtasks[f].emitted)
                      for f in c.active}
            raise AssertionError(
                f'fused scheduler lost windows for {c.path}: '
                f'{counts} (done, emitted) per family, '
                f'exhausted={c.exhausted}')

    def doom(fam: str, prov, valid: int, stage: str) -> None:
        # a family's device fault fails ITS subtasks only — the shared
        # decode keeps feeding the other families
        from video_features_tpu.obs.events import log_batch_error
        log_batch_error(sorted({str(c.path) for c, _ in prov}), valid,
                        fam_batch[fam], stage=f'{stage}:{fam}')
        for c, _ in prov:
            sub = c.subtasks[fam]
            sub.failed = True
            sub.done += 1

    def sync_oldest(fam: str) -> None:
        ex = exs[fam]
        out_dev, prov, valid, batch_videos = pending[fam].popleft()
        ex._inflight_now = len(pending[fam])
        try:
            with ex.tracer.stage('d2h', videos=batch_videos,
                                 valid=valid, capacity=fam_batch[fam],
                                 family=fam):
                out = ex.fetch_outputs(out_dev)
        except KeyboardInterrupt:
            raise
        except Exception:
            doom(fam, prov, valid, 'd2h')
            sweep()
            return
        ex.tracer.add_occupancy('d2h', valid, fam_batch[fam])
        for i, (c, meta) in enumerate(prov):
            f2, t_ms = meta
            sub = c.subtasks[f2]
            sub.done += 1
            if sub.failed or c.failed:
                continue
            for key, arr in out.items():
                sub.rows.setdefault(key, []).append(arr[i])
            sub.meta_rows.append(t_ms)
        sweep()

    def drain_all() -> None:
        for fam in fams:
            while pending[fam]:
                sync_oldest(fam)

    for stacked, prov, valid in packed_batches(
            ahead, max_batch, max_pool_age_s=max_pool_age_s,
            tracer=lead.tracer, family_of=lambda m: m[0],
            family_batch=fam_batch):
        if stacked is None:
            # batchless drain marker (NUDGE / post-FLUSH): materialize
            # every family's in-flight queue, then finalize
            drain_all()
            sweep()
            continue
        fam = prov[0][1][0]
        ex = exs[fam]
        batch_videos = (sorted({str(c.path) for c, _ in prov})
                        if ex.tracer.enabled else None)
        try:
            # per-batch precision scope: adjacent batches may belong to
            # families on different precision lanes
            with ex.precision_scope():
                with ex.tracer.stage('h2d', videos=batch_videos,
                                     valid=valid,
                                     capacity=fam_batch[fam],
                                     family=fam):
                    dev = ex.put_input(stacked)
                with ex.tracer.stage('model', videos=batch_videos,
                                     valid=valid,
                                     capacity=fam_batch[fam],
                                     family=fam):
                    out = ex.packed_step(dev)
        except KeyboardInterrupt:
            raise
        except Exception:
            doom(fam, prov, valid, 'model')
            sweep()
            continue
        ex.tracer.add_occupancy('model', valid, fam_batch[fam])
        if manifests[fam] is not None:
            shape = getattr(dev, 'shape', None)
            if shape is not None:
                cd = str(getattr(ex, 'compute_dtype', 'float32'))
                lane = '' if cd == 'float32' else f':{cd}'
                identity = (f'{fam}:{tuple(shape)}:'
                            f'{getattr(dev, "dtype", "")}{lane}')
                costed[fam].setdefault(
                    identity, (tuple(shape), getattr(dev, 'dtype', None)))
        pending[fam].append((out, prov, valid, batch_videos))
        ex._inflight_now = len(pending[fam])
        while len(pending[fam]) >= depth[fam]:
            sync_oldest(fam)
    drain_all()
    for ex in exs.values():
        ex._inflight_now = 0
    sweep(final=True)

    for fam, ex in exs.items():
        manifest = manifests[fam]
        if manifest is not None and costed[fam]:
            import jax
            for identity, (shape, dtype) in costed[fam].items():
                info: Dict = {'batch': fam_batch[fam],
                              'compute_dtype':
                                  str(getattr(ex, 'compute_dtype',
                                              'float32'))}
                cost = (ex.executable_cost(
                            jax.ShapeDtypeStruct(shape, dtype))
                        if dtype is not None else None)
                if cost:
                    info.update(cost)
                manifest.note_executable(identity, info)
        if farm is not None and manifest is not None:
            manifest.note_farm({'decode_workers': farm.n_workers,
                                'ring_bytes_per_worker': farm.ring_bytes,
                                'stats': farm.stats(),
                                'fused_families': fams})
        if ex.tracer.enabled and ex.tracer.report():
            if manifest is not None:
                manifest.fold_stages(ex.tracer.report())
            if getattr(ex, 'profile', True):
                print(f'--- stage timing: fused worklist '
                      f'[{fam}] ({n_started[0]} videos, batch '
                      f'{fam_batch[fam]})', file=sys.stderr)
                print(ex.tracer.summary(), file=sys.stderr)
            if ex is not lead:
                ex.tracer.reset()
    if lead.tracer.enabled:
        lead.tracer.reset()
