"""HuggingFace `transformers` checkpoint → timm-layout state dicts.

The native timm-layout families (models/{vit,convnext,swin,regnet}.py)
load torch checkpoints in timm naming. pip-timm is one provisioning path
(extract/timm.py bridge); this module is another that needs only
`transformers`-layout checkpoints — HF hosts the same published
architectures under a different module tree, and the re-keying is
mechanical. Used by ``tools/convert_checkpoint.py --hf-family`` and
validated end-to-end against `transformers`' own forward passes in
``tests/test_hf_crosscheck.py`` (~1e-7-class rel L2).

Functions take a flat HF state dict (torch tensors or numpy arrays) and
return a timm-named dict ready for ``transplant()``. Structural deltas
handled per family (six: vit, deit, beit, convnext, swin, regnet):

  * vit: HF splits q/k/v projections; timm packs ``qkv``.
  * deit: the vit mapping plus HF's ``distillation_token`` → timm
    ``dist_token`` (timm DeiT names like ``deit_tiny_distilled_patch16_224``
    resolve to their underlying vit geometry automatically).
  * convnext: HF calls blocks ``layers`` and the timm ``gamma`` layer
    scale ``layer_scale_parameter``; the head LN is HF's pooler norm.
  * swin: q/k/v packing as vit, plus HF hangs each PatchMerging off the
    END of stage L where timm 0.9.12 puts it at the START of stage L+1.
  * regnet: HF nests each block's conv stack in a Sequential
    (layer.0/1/3 = conv1/conv2/conv3, layer.2 = SE) and calls the
    projection ``shortcut``.
  * beit: q/k/v split as vit but k carries NO bias (timm packs
    ``q_bias``/``v_bias``); HF names the layer scales
    ``lambda_1``/``lambda_2`` (timm ``gamma_1``/``gamma_2``), hangs the
    relative position bias table under
    ``attention.attention.relative_position_bias``, and the timm
    ``fc_norm`` is HF's pooler layernorm.
"""
from __future__ import annotations

from typing import Any, Dict

Sd = Dict[str, Any]


def _cat0(parts):
    first = parts[0]
    if hasattr(first, 'detach'):     # torch tensor
        import torch
        return torch.cat(list(parts), dim=0)
    import numpy as np
    return np.concatenate(list(parts), axis=0)


def _t2(x):
    """2-D transpose for a torch tensor or numpy array."""
    if hasattr(x, 'detach'):
        return x.detach().t().contiguous()
    import numpy as np
    return np.ascontiguousarray(np.asarray(x).T)


# key stems every supported HF backbone subtree contains at top level —
# the guard that a candidate prefix really wraps a backbone, not some
# unrelated module that happens to be named e.g. 'model'
_BACKBONE_MARKERS = ('embeddings.', 'encoder.', 'embedder.')
# keys legitimately discarded when unwrapping a *ForImageClassification
# checkpoint (the task head the feature path never uses)
_EXPECTED_DISCARDS = ('classifier.',)


def strip_task_prefix(hf_sd: Sd) -> Sd:
    """Drop a task-model wrapper: ``vit.``/``swin.``/... key prefixes from
    *ForImageClassification checkpoints (and their classifier head).

    Only strips when the prefixed subtree actually looks like a backbone
    (contains an ``embeddings.``/``encoder.`` stem), and refuses to
    silently discard keys outside the prefix other than the classifier
    head — a mixed or unexpectedly-named checkpoint errors instead of
    being mangled."""
    prefixes = {k.split('.', 1)[0] for k in hf_sd if '.' in k}
    for p in ('vit', 'deit', 'beit', 'swin', 'convnext', 'regnet', 'model'):
        if p not in prefixes:
            continue
        sub = {k[len(p) + 1:]: v for k, v in hf_sd.items()
               if k.startswith(p + '.')}
        if not any(k.startswith(_BACKBONE_MARKERS) for k in sub):
            continue  # a coincidental module name, not the backbone wrapper
        dropped = [k for k in hf_sd
                   if not k.startswith(p + '.')
                   and not k.startswith(_EXPECTED_DISCARDS)]
        if dropped:
            raise ValueError(
                f'checkpoint mixes {p}.*-prefixed backbone keys with '
                f'unprefixed keys that are not a classifier head '
                f'(e.g. {dropped[:3]}); refusing to silently discard them')
        return sub
    return hf_sd


def vit_to_timm(hf_sd: Sd, arch: str) -> Sd:
    """transformers.ViTModel → timm VisionTransformer naming."""
    from video_features_tpu.models.vit import ARCHS
    depth = ARCHS[arch]['layers']
    sd = {
        'cls_token': hf_sd['embeddings.cls_token'],
        'pos_embed': hf_sd['embeddings.position_embeddings'],
        'patch_embed.proj.weight':
            hf_sd['embeddings.patch_embeddings.projection.weight'],
        'patch_embed.proj.bias':
            hf_sd['embeddings.patch_embeddings.projection.bias'],
        'norm.weight': hf_sd['layernorm.weight'],
        'norm.bias': hf_sd['layernorm.bias'],
    }
    for i in range(depth):
        h, t = f'encoder.layer.{i}.', f'blocks.{i}.'
        for ours, theirs in [('norm1', 'layernorm_before'),
                             ('norm2', 'layernorm_after'),
                             ('attn.proj', 'attention.output.dense'),
                             ('mlp.fc1', 'intermediate.dense'),
                             ('mlp.fc2', 'output.dense')]:
            sd[t + ours + '.weight'] = hf_sd[h + theirs + '.weight']
            sd[t + ours + '.bias'] = hf_sd[h + theirs + '.bias']
        for p in ('weight', 'bias'):
            sd[t + f'attn.qkv.{p}'] = _cat0(
                [hf_sd[h + f'attention.attention.{proj}.{p}']
                 for proj in ('query', 'key', 'value')])
    return sd


def deit_to_timm(hf_sd: Sd, arch: str) -> Sd:
    """transformers.DeiTModel (distilled) → timm
    VisionTransformerDistilled naming: the ViT mapping plus the
    distillation token (timm ``dist_token``); the 2-slot prefix rides
    ``position_embeddings`` unchanged. ``arch`` may be the timm DeiT name
    (``deit_tiny_distilled_patch16_224``) or its underlying vit geometry —
    DeiT IS timm's VisionTransformer (extract/timm.py aliases them)."""
    if arch.startswith('deit'):
        arch = arch.replace('deit', 'vit', 1).replace('_distilled', '')
    sd = vit_to_timm(hf_sd, arch)
    sd['dist_token'] = hf_sd['embeddings.distillation_token']
    return sd


def beit_to_timm(hf_sd: Sd, arch: str) -> Sd:
    """transformers.BeitModel → timm Beit naming. HF registers the
    ``relative_position_index`` buffers non-persistent, so they are
    regenerated here from the arch geometry (the published BEiT formula —
    identical in timm, HF, and models/beit.py)."""
    from video_features_tpu.models.beit import (
        ARCHS, INPUT_RESOLUTION, gen_relative_position_index,
    )
    depth = ARCHS[arch]['layers']
    side = INPUT_RESOLUTION // ARCHS[arch]['patch']
    index = gen_relative_position_index((side, side))
    sd = {
        'cls_token': hf_sd['embeddings.cls_token'],
        'patch_embed.proj.weight':
            hf_sd['embeddings.patch_embeddings.projection.weight'],
        'patch_embed.proj.bias':
            hf_sd['embeddings.patch_embeddings.projection.bias'],
        'fc_norm.weight': hf_sd['pooler.layernorm.weight'],
        'fc_norm.bias': hf_sd['pooler.layernorm.bias'],
    }
    for i in range(depth):
        h, t = f'encoder.layer.{i}.', f'blocks.{i}.'
        a = h + 'attention.attention.'
        sd[t + 'attn.qkv.weight'] = _cat0(
            [hf_sd[a + f'{proj}.weight']
             for proj in ('query', 'key', 'value')])
        sd[t + 'attn.q_bias'] = hf_sd[a + 'query.bias']
        sd[t + 'attn.v_bias'] = hf_sd[a + 'value.bias']
        rb = a + 'relative_position_bias.'
        sd[t + 'attn.relative_position_bias_table'] = hf_sd[
            rb + 'relative_position_bias_table']
        sd[t + 'attn.relative_position_index'] = index
        sd[t + 'gamma_1'] = hf_sd[h + 'lambda_1']
        sd[t + 'gamma_2'] = hf_sd[h + 'lambda_2']
        for ours, theirs in [('norm1', 'layernorm_before'),
                             ('norm2', 'layernorm_after'),
                             ('attn.proj', 'attention.output.dense'),
                             ('mlp.fc1', 'intermediate.dense'),
                             ('mlp.fc2', 'output.dense')]:
            sd[t + ours + '.weight'] = hf_sd[h + theirs + '.weight']
            sd[t + ours + '.bias'] = hf_sd[h + theirs + '.bias']
    return sd


def convnext_to_timm(hf_sd: Sd, arch: str) -> Sd:
    """transformers.ConvNextModel → timm ConvNeXt naming."""
    from video_features_tpu.models.convnext import ARCHS
    depths = ARCHS[arch]['depths']
    sd = {
        'stem.0.weight': hf_sd['embeddings.patch_embeddings.weight'],
        'stem.0.bias': hf_sd['embeddings.patch_embeddings.bias'],
        'stem.1.weight': hf_sd['embeddings.layernorm.weight'],
        'stem.1.bias': hf_sd['embeddings.layernorm.bias'],
        'head.norm.weight': hf_sd['layernorm.weight'],
        'head.norm.bias': hf_sd['layernorm.bias'],
    }
    for s, depth in enumerate(depths):
        h, t = f'encoder.stages.{s}.', f'stages.{s}.'
        if s > 0:
            for idx in ('0', '1'):
                for p in ('weight', 'bias'):
                    sd[f'{t}downsample.{idx}.{p}'] = hf_sd[
                        f'{h}downsampling_layer.{idx}.{p}']
        for j in range(depth):
            hb, tb = f'{h}layers.{j}.', f'{t}blocks.{j}.'
            sd[tb + 'gamma'] = hf_sd[hb + 'layer_scale_parameter']
            for ours, theirs in [('conv_dw', 'dwconv'),
                                 ('norm', 'layernorm'),
                                 ('mlp.fc1', 'pwconv1'),
                                 ('mlp.fc2', 'pwconv2')]:
                sd[tb + ours + '.weight'] = hf_sd[hb + theirs + '.weight']
                sd[tb + ours + '.bias'] = hf_sd[hb + theirs + '.bias']
    return sd


def swin_to_timm(hf_sd: Sd, arch: str) -> Sd:
    """transformers.SwinModel → timm 0.9.12 Swin naming."""
    from video_features_tpu.models.swin import ARCHS
    depths = ARCHS[arch]['depths']
    sd = {
        'patch_embed.proj.weight':
            hf_sd['embeddings.patch_embeddings.projection.weight'],
        'patch_embed.proj.bias':
            hf_sd['embeddings.patch_embeddings.projection.bias'],
        'patch_embed.norm.weight': hf_sd['embeddings.norm.weight'],
        'patch_embed.norm.bias': hf_sd['embeddings.norm.bias'],
        'norm.weight': hf_sd['layernorm.weight'],
        'norm.bias': hf_sd['layernorm.bias'],
    }
    for li, depth in enumerate(depths):
        if li > 0:   # HF stage li-1's tail merge == timm stage li's head
            for name in ('norm', 'reduction'):
                for p in ('weight', 'bias'):
                    key = f'encoder.layers.{li - 1}.downsample.{name}.{p}'
                    if key in hf_sd:   # reduction has no bias
                        sd[f'layers.{li}.downsample.{name}.{p}'] = hf_sd[key]
        for b in range(depth):
            h = f'encoder.layers.{li}.blocks.{b}.'
            t = f'layers.{li}.blocks.{b}.'
            sd[t + 'attn.relative_position_bias_table'] = hf_sd[
                h + 'attention.self.relative_position_bias_table']
            for p in ('weight', 'bias'):
                sd[t + f'attn.qkv.{p}'] = _cat0(
                    [hf_sd[h + f'attention.self.{proj}.{p}']
                     for proj in ('query', 'key', 'value')])
            for ours, theirs in [('norm1', 'layernorm_before'),
                                 ('norm2', 'layernorm_after'),
                                 ('attn.proj', 'attention.output.dense'),
                                 ('mlp.fc1', 'intermediate.dense'),
                                 ('mlp.fc2', 'output.dense')]:
                sd[t + ours + '.weight'] = hf_sd[h + theirs + '.weight']
                sd[t + ours + '.bias'] = hf_sd[h + theirs + '.bias']
    return sd


def regnet_to_timm(hf_sd: Sd, arch: str) -> Sd:
    """transformers.RegNetModel → timm RegNet naming. Handles both layer
    types the way the checkpoint dictates: 'y' blocks nest conv1/conv2/
    SE/conv3 as layer.0/1/2/3, SE-free 'x' blocks as layer.0/1/2."""
    from video_features_tpu.models.regnet import ARCHS
    depths = ARCHS[arch][0]
    sd: Sd = {}

    def cna(t, h):
        sd[f'{t}.conv.weight'] = hf_sd[f'{h}.convolution.weight']
        for p in ('weight', 'bias', 'running_mean', 'running_var'):
            sd[f'{t}.bn.{p}'] = hf_sd[f'{h}.normalization.{p}']

    cna('stem', 'embedder.embedder')
    for si, depth in enumerate(depths):
        for j in range(depth):
            h = f'encoder.stages.{si}.layers.{j}'
            t = f's{si + 1}.b{j + 1}'
            cna(f'{t}.conv1', f'{h}.layer.0')
            cna(f'{t}.conv2', f'{h}.layer.1')
            has_se = f'{h}.layer.2.attention.0.weight' in hf_sd
            cna(f'{t}.conv3', f'{h}.layer.{3 if has_se else 2}')
            if has_se:
                for ours, theirs in [('fc1', 'attention.0'),
                                     ('fc2', 'attention.2')]:
                    for p in ('weight', 'bias'):
                        sd[f'{t}.se.{ours}.{p}'] = hf_sd[
                            f'{h}.layer.2.{theirs}.{p}']
            if f'{h}.shortcut.convolution.weight' in hf_sd:
                cna(f'{t}.downsample', f'{h}.shortcut')
    return sd


def clip_to_openai(hf_sd: Sd, arch: str = '') -> Sd:
    """transformers.CLIPModel → OpenAI CLIP state-dict naming (the layout
    models/clip.py consumes; reference models/clip/clip_src/model.py).

    HF splits q/k/v where OpenAI fuses ``attn.in_proj_*``; HF's projection
    heads are F.linear weights (out, in) where OpenAI's ``visual.proj`` /
    ``text_projection`` are raw right-operands (in, out) — transposed here.
    Transplant the result with ``no_transpose=clip.NO_TRANSPOSE`` exactly
    like an OpenAI checkpoint. ``arch`` is unused (geometry is read off the
    keys); accepted for CONVERTERS signature uniformity."""
    del arch
    sd: Sd = {'logit_scale': hf_sd['logit_scale']}

    def block(dst: str, src: str) -> None:
        sd[f'{dst}.attn.in_proj_weight'] = _cat0(
            [hf_sd[f'{src}.self_attn.{p}_proj.weight'] for p in 'qkv'])
        sd[f'{dst}.attn.in_proj_bias'] = _cat0(
            [hf_sd[f'{src}.self_attn.{p}_proj.bias'] for p in 'qkv'])
        for ours, theirs in [('attn.out_proj', 'self_attn.out_proj'),
                             ('ln_1', 'layer_norm1'), ('ln_2', 'layer_norm2'),
                             ('mlp.c_fc', 'mlp.fc1'),
                             ('mlp.c_proj', 'mlp.fc2')]:
            for p in ('weight', 'bias'):
                sd[f'{dst}.{ours}.{p}'] = hf_sd[f'{src}.{theirs}.{p}']

    def depth(tower: str) -> int:
        return 1 + max(int(k.split('.')[3]) for k in hf_sd
                       if k.startswith(f'{tower}.encoder.layers.'))

    # visual tower (HF spells the pre-LN 'pre_layrnorm' historically)
    v = 'vision_model.'
    pre = v + ('pre_layrnorm' if v + 'pre_layrnorm.weight' in hf_sd
               else 'pre_layernorm')
    sd['visual.conv1.weight'] = hf_sd[v + 'embeddings.patch_embedding.weight']
    sd['visual.class_embedding'] = hf_sd[v + 'embeddings.class_embedding']
    sd['visual.positional_embedding'] = hf_sd[
        v + 'embeddings.position_embedding.weight']
    for p in ('weight', 'bias'):
        sd[f'visual.ln_pre.{p}'] = hf_sd[f'{pre}.{p}']
        sd[f'visual.ln_post.{p}'] = hf_sd[f'{v}post_layernorm.{p}']
    for i in range(depth('vision_model')):
        block(f'visual.transformer.resblocks.{i}', f'{v}encoder.layers.{i}')
    sd['visual.proj'] = _t2(hf_sd['visual_projection.weight'])

    # text tower
    t = 'text_model.'
    sd['token_embedding.weight'] = hf_sd[
        t + 'embeddings.token_embedding.weight']
    sd['positional_embedding'] = hf_sd[
        t + 'embeddings.position_embedding.weight']
    for p in ('weight', 'bias'):
        sd[f'ln_final.{p}'] = hf_sd[f'{t}final_layer_norm.{p}']
    for i in range(depth('text_model')):
        block(f'transformer.resblocks.{i}', f'{t}encoder.layers.{i}')
    sd['text_projection'] = _t2(hf_sd['text_projection.weight'])
    return sd


CONVERTERS = {
    'vit': vit_to_timm,
    'deit': deit_to_timm,
    'beit': beit_to_timm,
    'convnext': convnext_to_timm,
    'swin': swin_to_timm,
    'regnet': regnet_to_timm,
}


def hf_to_timm(family: str, hf_sd: Sd, arch: str) -> Sd:
    """Re-key a `transformers` state dict into timm naming for ``arch``.

    ``family`` is one of CONVERTERS; task-model prefixes (e.g.
    ``vit.encoder...`` from *ForImageClassification) are stripped first.
    """
    if family not in CONVERTERS:
        raise ValueError(
            f'hf-family {family!r} not supported: {sorted(CONVERTERS)}')
    return CONVERTERS[family](strip_task_prefix(hf_sd), arch)
