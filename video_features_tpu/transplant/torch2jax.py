"""PyTorch checkpoint → JAX pytree transplant layer.

The params pytree of every model in this framework mirrors the source torch
``state_dict``: keys are split on '.' into a nested dict, and kernels are
re-laid-out once at load time into TPU-native channels-last form:

  * ConvNd weight (O, I, *spatial)  →  (*spatial, I, O)   (HWIO / DHWIO)
  * Linear weight (O, I)            →  (I, O)
  * everything else (biases, norm stats) unchanged.

This makes the converter mechanical for all model families and lets parity
tests transplant a randomly-initialized reference torch module directly
(SURVEY.md §5.4: conv layout transpose, DataParallel prefixes, fp16 params).
"""
from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import numpy as np


def to_numpy(tensor: Any) -> np.ndarray:
    """torch.Tensor / array-like → float32-preserving numpy array."""
    if hasattr(tensor, 'detach'):
        tensor = tensor.detach().cpu().numpy()
    return np.asarray(tensor)


def strip_dataparallel(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Remove 'module.' DataParallel prefixes (reference utils/utils.py:243-249).

    Unlike the reference helper, keys without the prefix are KEPT (the
    reference silently drops them — a footgun for mixed checkpoints).
    """
    out = {}
    for k, v in state_dict.items():
        out[k[len('module.'):] if k.startswith('module.') else k] = v
    return out


def convert_tensor(name: str, value: Any,
                   no_transpose: Optional[set] = None) -> np.ndarray:
    """Apply the layout rule for one state_dict entry.

    ``no_transpose`` lists names whose 2-D '.weight' is a gather table or a
    raw matmul-right operand and must keep torch layout (e.g. CLIP's
    ``token_embedding.weight``).
    """
    arr = to_numpy(value)
    if no_transpose and name in no_transpose:
        return arr
    if name.endswith('.weight') or name == 'weight':
        if arr.ndim >= 3:            # convNd (O, I, *spatial) → (*spatial, I, O)
            axes = tuple(range(2, arr.ndim)) + (1, 0)
            return np.ascontiguousarray(arr.transpose(axes))
        if arr.ndim == 2:            # linear (O, I) → (I, O)
            return np.ascontiguousarray(arr.T)
    return arr


def nest(flat: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    """{'a.b.c': x} → {'a': {'b': {'c': x}}}."""
    tree: Dict[str, Any] = {}
    for key, value in flat.items():
        node = tree
        parts = key.split('.')
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree


def transplant(state_dict: Mapping[str, Any],
               no_transpose: Optional[set] = None,
               dtype: Optional[np.dtype] = None,
               scales: Optional[Mapping[str, np.ndarray]] = None,
               ) -> Dict[str, Any]:
    """Full pipeline: strip DP prefixes, convert layouts, nest, cast.

    Args:
        state_dict: torch state_dict (or any {name: tensor} mapping).
        no_transpose: names whose 2-D '.weight' must keep torch layout
            (embedding tables; see :func:`convert_tensor`).
        dtype: optional cast (e.g. np.float32 for CLIP's fp16 checkpoints).
            ``np.int8`` selects the int8 WEIGHT-QUANTIZATION path instead
            of a blanket astype: eligible conv/linear weights become
            :class:`~video_features_tpu.ops.quant.QuantizedTensor` leaves
            (per-output-channel symmetric, post-re-layout so the channel
            axis is last), everything else stays float32 — the lane's
            declared fp32 minority (ops/quant.py).
        scales: pinned per-tensor int8 scale table (dot-named, from
            ``tools/calibrate_int8.py`` via
            :func:`~video_features_tpu.ops.quant.load_scale_table`);
            int8 dtype only. Absent entries use the derived weight-amax
            scales — deterministic either way.
    """
    no_transpose = set(no_transpose or ())
    quantize = dtype is not None and np.dtype(dtype) == np.int8
    flat = {}
    for name, value in strip_dataparallel(state_dict).items():
        if name.endswith('num_batches_tracked'):
            continue  # torch BN bookkeeping, meaningless at inference
        arr = convert_tensor(name, value, no_transpose)
        if (not quantize and dtype is not None
                and np.issubdtype(arr.dtype, np.floating)):
            arr = arr.astype(dtype)
        flat[name] = arr
    if quantize:
        from video_features_tpu.ops.quant import quantize_flat
        flat = quantize_flat(flat, skip=no_transpose, scales=scales)
    return nest(flat)


def load_torch_checkpoint(path: str, dtype: Optional[np.dtype] = np.float32,
                          key: Optional[str] = None,
                          no_transpose: Optional[set] = None) -> Dict[str, Any]:
    """Load a checkpoint and transplant it to a JAX pytree.

    ``.pt``/``.pth`` files are read via torch (CPU build is enough).
    ``.npz`` files are pre-transplanted archives written by
    :func:`save_transplanted` (or tools/convert_checkpoint.py) — loading
    them needs NO torch at all, which is how production TPU hosts deploy.
    ``key`` selects a sub-dict for torch checkpoints that wrap the
    state_dict (e.g. {'state_dict': ...} or {'model': ...}).

    ``dtype=np.int8`` quantizes eligible weights instead of casting
    (see :func:`transplant`); a pinned scale table sitting next to the
    checkpoint (``<ckpt>.int8-scales.npz``, written by
    tools/calibrate_int8.py) is consumed automatically.
    """
    quantize = dtype is not None and np.dtype(dtype) == np.int8
    scales = None
    if quantize:
        from video_features_tpu.ops.quant import (
            load_scale_table, scale_table_path,
        )
        scales = load_scale_table(scale_table_path(str(path))) or None
    if str(path).endswith('.npz'):
        if key is not None or no_transpose is not None:
            raise ValueError(
                '.npz archives are already transplanted: key/no_transpose '
                'were applied at conversion time and cannot be re-applied')
        params = load_transplanted(path)
        if quantize:
            from video_features_tpu.ops.quant import quantize_flat
            return nest(quantize_flat(_flatten(params), scales=scales))
        if dtype is not None:
            def cast(tree):
                return {k: (cast(v) if isinstance(v, dict) else
                            (v.astype(dtype)
                             if np.issubdtype(v.dtype, np.floating) else v))
                        for k, v in tree.items()}
            params = cast(params)
        return params

    import torch

    ckpt = torch.load(path, map_location='cpu', weights_only=False)
    if key is not None:
        ckpt = ckpt[key]
    elif isinstance(ckpt, dict) and 'state_dict' in ckpt:
        ckpt = ckpt['state_dict']
    return transplant(ckpt, dtype=dtype, no_transpose=no_transpose,
                      scales=scales)


def _flatten(tree: Mapping[str, Any], prefix: str = '') -> Dict[str, np.ndarray]:
    flat: Dict[str, np.ndarray] = {}
    for k, v in tree.items():
        name = f'{prefix}{k}'
        if isinstance(v, Mapping):
            flat.update(_flatten(v, f'{name}.'))
        else:
            flat[name] = np.asarray(v)
    return flat


def save_transplanted(params: Mapping[str, Any], path: str) -> None:
    """Write a transplanted pytree as a flat .npz (dot-joined keys).

    The inverse of :func:`load_transplanted`; lets a torch-equipped machine
    convert checkpoints once so TPU hosts run torch-free.
    """
    np.savez(path, **_flatten(params))


def load_transplanted(path: str) -> Dict[str, Any]:
    """Read a :func:`save_transplanted` .npz back into the nested pytree."""
    with np.load(path) as data:
        return nest({k: data[k] for k in data.files})
