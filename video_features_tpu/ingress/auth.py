"""API-key tenancy for the ingress.

Keys live in a config-pointed file (``serve_ingress_auth_file``) — JSON
or YAML, reloaded only at startup (key rotation = restart/SIGHUP the
daemon; a file watch on a secrets file is more machinery than a front
door needs). Two accepted shapes::

    {"keys": {"<api-key>": {"tenant": "acme",
                            "priority": "interactive",
                            "rate_rps": 50, "burst": 100,
                            "max_concurrent": 8}}}

or the flat form ``{"<api-key>": {...}}``. Every field but ``tenant``
is optional: ``priority`` defaults to ``interactive``, a null/absent
``rate_rps`` means unlimited, ``max_concurrent`` defaults to unlimited.

Requests authenticate with ``Authorization: Bearer <key>`` or
``X-API-Key: <key>``. Key comparison is constant-time
(``hmac.compare_digest``) — the keys ARE the secret, and a timing
oracle on a network endpoint is a real leak.
"""
from __future__ import annotations

import hmac
from typing import Dict, Mapping, Optional

# the one canonical priority vocabulary (the server validates submits
# against it); re-exported here for auth-file validation
from video_features_tpu.serve.protocol import PRIORITIES


class Tenant:
    """One API key's identity + policy (immutable after load)."""

    __slots__ = ('name', 'priority', 'rate_rps', 'burst', 'max_concurrent')

    def __init__(self, name: str, priority: str = 'interactive',
                 rate_rps: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_concurrent: Optional[int] = None) -> None:
        if priority not in PRIORITIES:
            raise ValueError(
                f'tenant {name!r}: priority must be one of {PRIORITIES}; '
                f'got {priority!r}')
        self.name = str(name)
        self.priority = priority
        self.rate_rps = None if rate_rps is None else float(rate_rps)
        if self.rate_rps is not None and self.rate_rps <= 0:
            raise ValueError(f'tenant {name!r}: rate_rps must be > 0')
        # default burst: one second of rate (min 1) — a keyless knob
        # most operators never need to touch
        self.burst = (float(burst) if burst is not None
                      else max(self.rate_rps or 1.0, 1.0))
        self.max_concurrent = (None if max_concurrent is None
                               else int(max_concurrent))
        if self.max_concurrent is not None and self.max_concurrent < 0:
            raise ValueError(
                f'tenant {name!r}: max_concurrent must be >= 0')


class ApiKeyAuth:
    """The key table + header authentication."""

    def __init__(self, keys: Mapping[str, Tenant]) -> None:
        self._keys: Dict[str, Tenant] = dict(keys)
        if not self._keys:
            raise ValueError('auth file defines no API keys')

    @classmethod
    def from_file(cls, path: str) -> 'ApiKeyAuth':
        import yaml
        with open(path, encoding='utf-8') as f:
            doc = yaml.safe_load(f) or {}
        if not isinstance(doc, dict):
            raise ValueError(f'auth file {path} must be a mapping')
        table = doc.get('keys', doc)
        if not isinstance(table, dict):
            raise ValueError(f'auth file {path}: "keys" must be a mapping')
        keys: Dict[str, Tenant] = {}
        for key, spec in table.items():
            spec = dict(spec or {})
            tenant = spec.pop('tenant', None)
            if not tenant:
                raise ValueError(
                    f'auth file {path}: key {str(key)[:6]}… has no tenant')
            unknown = set(spec) - {'priority', 'rate_rps', 'burst',
                                   'max_concurrent'}
            if unknown:
                raise ValueError(
                    f'auth file {path}: tenant {tenant!r} has unknown '
                    f'fields {sorted(unknown)}')
            keys[str(key)] = Tenant(tenant, **spec)
        # several keys may share one tenant — and then they SHARE its
        # quota ledger (ingress/quota.py keys state by tenant name), so
        # their policies must agree or the effective policy would be
        # whichever key happened to authenticate first after startup
        by_tenant: Dict[str, tuple] = {}
        for t in keys.values():
            policy = (t.priority, t.rate_rps, t.burst, t.max_concurrent)
            prior = by_tenant.setdefault(t.name, policy)
            if prior != policy:
                raise ValueError(
                    f'auth file {path}: keys for tenant {t.name!r} carry '
                    'conflicting policies (priority/rate_rps/burst/'
                    'max_concurrent must match across a tenant\'s keys '
                    '— they share one quota ledger)')
        return cls(keys)

    @property
    def n_tenants(self) -> int:
        return len({t.name for t in self._keys.values()})

    def authenticate(self, headers: Mapping[str, str]) -> Optional[Tenant]:
        """The tenant behind this request's credentials, or None."""
        key = None
        bearer = headers.get('authorization', '')
        if bearer.lower().startswith('bearer '):
            key = bearer[7:].strip()
        if not key:
            key = headers.get('x-api-key', '').strip()
        if not key:
            return None
        for known, tenant in self._keys.items():
            if hmac.compare_digest(known.encode(), key.encode()):
                return tenant
        return None
