"""Minimal HTTP/1.1 transport for the ingress (stdlib-only).

One request per connection (``Connection: close``) — the endpoint fronts
multi-second extraction requests and long-lived live sessions, so
keep-alive buys nothing and drops a whole class of pipelining bugs. The
pieces the gateway composes:

  * :func:`read_request` — request-line + header framing with hard
    bounds; an oversized declared body is rejected with a STRUCTURED
    413-style error (:class:`HttpError`) before a byte of it is read,
    instead of crashing (or OOMing) the reader;
  * :func:`read_chunked` / :func:`iter_chunks` — chunked request bodies
    (live sessions stream frames up in chunks);
  * :class:`ResponseWriter` — fixed and chunked responses; chunk writes
    are lock-serialized because live sessions write from two threads
    (the handler streaming status + the device loop streaming windows);
  * :class:`HttpServer` — accept loop with a bounded handler pool
    (excess connections get an immediate 503) and a two-phase drain:
    ``begin_drain`` stops accepting, ``finish_drain`` force-closes
    whatever half-open connections remain so no abandoned client pins a
    handler thread (or a warm-pool entry) past shutdown.
"""
from __future__ import annotations

import json
import socket
import threading
from typing import Any, Callable, Dict, Iterator, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

MAX_REQUEST_LINE = 8192
MAX_HEADER_BYTES = 32768
MAX_HEADERS = 100

# The named status vocabulary of the ingress wire surface. Every status
# an ingress module emits comes from THESE names (vft-lint's
# wire-literal rule rejects inline ints in status positions outside
# this module), which is what lets the vft-wire extractor
# (analysis/wire.py) resolve the per-route status-code sets it pins in
# WIRE.lock.json — an inline 418 would be invisible drift.
OK = 200
BAD_REQUEST = 400
UNAUTHORIZED = 401
FORBIDDEN = 403
NOT_FOUND = 404
METHOD_NOT_ALLOWED = 405
CONFLICT = 409
PAYLOAD_TOO_LARGE = 413
TOO_MANY_REQUESTS = 429
HEADERS_TOO_LARGE = 431
# nginx convention: the client went away mid-request — never sent on
# the wire, only a metrics label (vft_ingress_requests_total{code=})
CLIENT_CLOSED = 499
INTERNAL_ERROR = 500
SERVICE_UNAVAILABLE = 503

# HTTP status → reason phrases we actually emit
# thread-discipline declaration (vft-lint): write-once constants need
# no lock — nothing mutates them after import
_LOCKED_BY = {'_REASONS': 'immutable'}
_REASONS = {OK: 'OK', BAD_REQUEST: 'Bad Request',
            UNAUTHORIZED: 'Unauthorized',
            FORBIDDEN: 'Forbidden', NOT_FOUND: 'Not Found',
            METHOD_NOT_ALLOWED: 'Method Not Allowed',
            CONFLICT: 'Conflict', PAYLOAD_TOO_LARGE: 'Payload Too Large',
            TOO_MANY_REQUESTS: 'Too Many Requests',
            HEADERS_TOO_LARGE: 'Request Header Fields Too Large',
            INTERNAL_ERROR: 'Internal Server Error',
            SERVICE_UNAVAILABLE: 'Service Unavailable'}


class HttpError(Exception):
    """A request-level failure with a structured JSON body: ``status``
    is the HTTP code, ``code`` a machine-readable slug (``body_too_
    large``, ``bad_request`` …), ``extra`` rides into the body."""

    def __init__(self, status: int, code: str, message: str,
                 **extra: Any) -> None:
        super().__init__(message)
        self.status = status
        self.code = code
        self.extra = dict(extra)

    def body(self) -> Dict[str, Any]:
        out = {'ok': False, 'error': self.code, 'message': str(self)}
        out.update(self.extra)
        return out


class HttpRequest:
    """One parsed request head; the body stays ON THE WIRE until the
    handler asks for it (``read_body`` / ``iter_chunks``), so a rejected
    request never pays for — or buffers — its payload."""

    def __init__(self, method: str, target: str, rfile,
                 headers: Dict[str, str]) -> None:
        self.method = method
        parts = urlsplit(target)
        self.path = unquote(parts.path)
        self.query: Dict[str, str] = {
            k: v[-1] for k, v in parse_qs(parts.query).items()}
        self.headers = headers
        self._rfile = rfile

    @property
    def chunked(self) -> bool:
        return 'chunked' in self.headers.get('transfer-encoding',
                                             '').lower()

    def content_length(self) -> Optional[int]:
        raw = self.headers.get('content-length')
        if raw is None:
            return None
        try:
            n = int(raw)
        except ValueError:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'malformed Content-Length {raw!r}')
        if n < 0:
            raise HttpError(BAD_REQUEST, 'bad_request', 'negative Content-Length')
        return n

    def read_body(self, max_bytes: int) -> bytes:
        """The whole (non-chunked) body, bounded. The bound is checked
        against the DECLARED length first — an over-limit body is
        rejected without reading it."""
        if self.chunked:
            return read_chunked(self._rfile, max_bytes)
        n = self.content_length() or 0
        if n > max_bytes:
            raise HttpError(PAYLOAD_TOO_LARGE, 'body_too_large',
                            f'request body is {n} bytes; the ingress '
                            f'accepts at most {max_bytes}',
                            max_bytes=max_bytes, got_bytes=n)
        body = self._rfile.read(n) if n else b''
        if len(body) != n:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'connection closed mid-body')
        return body

    def json_body(self, max_bytes: int) -> Dict[str, Any]:
        body = self.read_body(max_bytes)
        if not body:
            return {}
        try:
            obj = json.loads(body.decode('utf-8'))
        except (ValueError, UnicodeDecodeError) as e:
            raise HttpError(BAD_REQUEST, 'bad_request', f'malformed JSON body: {e}')
        if not isinstance(obj, dict):
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'request body must be a JSON object')
        return obj

    def iter_chunks(self, max_chunk_bytes: int) -> Iterator[bytes]:
        """The chunked body, one wire chunk at a time (live sessions:
        each chunk is one client message). Ends after the zero-length
        terminator chunk."""
        if not self.chunked:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'this endpoint requires Transfer-Encoding: '
                            'chunked')
        return iter_chunks(self._rfile, max_chunk_bytes)


def read_request(rfile) -> Optional[HttpRequest]:
    """Parse one request head off ``rfile``; None on a cleanly closed
    connection (client connected and went away without sending)."""
    line = rfile.readline(MAX_REQUEST_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_REQUEST_LINE:
        raise HttpError(BAD_REQUEST, 'bad_request', 'request line too long')
    try:
        method, target, version = line.decode('latin-1').split()
    except ValueError:
        raise HttpError(BAD_REQUEST, 'bad_request',
                        f'malformed request line {line!r}')
    if not version.startswith('HTTP/1.'):
        raise HttpError(BAD_REQUEST, 'bad_request',
                        f'unsupported HTTP version {version!r}')
    headers: Dict[str, str] = {}
    total = 0
    for _ in range(MAX_HEADERS + 1):
        raw = rfile.readline(MAX_HEADER_BYTES + 1)
        total += len(raw)
        if total > MAX_HEADER_BYTES:
            raise HttpError(HEADERS_TOO_LARGE, 'headers_too_large',
                            'header block too large')
        if raw in (b'\r\n', b'\n', b''):
            break
        try:
            name, _, value = raw.decode('latin-1').partition(':')
        except UnicodeDecodeError:
            raise HttpError(BAD_REQUEST, 'bad_request', 'undecodable header')
        headers[name.strip().lower()] = value.strip()
    else:
        raise HttpError(BAD_REQUEST, 'bad_request', 'too many headers')
    return HttpRequest(method.upper(), target, rfile, headers)


def read_chunked(rfile, max_bytes: int) -> bytes:
    """Assemble a whole chunked body, bounded at ``max_bytes`` TOTAL."""
    out = []
    total = 0
    for chunk in iter_chunks(rfile, max_bytes):
        total += len(chunk)
        if total > max_bytes:
            raise HttpError(PAYLOAD_TOO_LARGE, 'body_too_large',
                            f'chunked body exceeded {max_bytes} bytes',
                            max_bytes=max_bytes)
        out.append(chunk)
    return b''.join(out)


def iter_chunks(rfile, max_chunk_bytes: int) -> Iterator[bytes]:
    """Yield each wire chunk of a chunked body; stops after the
    terminator. A single chunk larger than ``max_chunk_bytes`` is a
    structured 413 — the reader never buffers unbounded client input."""
    while True:
        size_line = rfile.readline(64)
        if not size_line:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'connection closed mid-chunked-body')
        if not size_line.endswith(b'\n'):
            # readline hit its bound mid-line (an over-long chunk
            # extension): parsing the size anyway would leave the line's
            # tail to be consumed as payload — misframed forever after
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'chunk-size line too long')
        try:
            size = int(size_line.split(b';', 1)[0].strip(), 16)
        except ValueError:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'malformed chunk size {size_line!r}')
        if size < 0:
            # int(_, 16) happily parses '-1'; rfile.read(-1) would then
            # buffer to EOF — the exact unbounded read the max-chunk
            # bound exists to prevent
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'negative chunk size {size_line!r}')
        if size > max_chunk_bytes:
            raise HttpError(PAYLOAD_TOO_LARGE, 'body_too_large',
                            f'chunk of {size} bytes exceeds the '
                            f'{max_chunk_bytes}-byte bound',
                            max_bytes=max_chunk_bytes, got_bytes=size)
        if size == 0:
            rfile.readline(8)           # trailing CRLF (no trailers)
            return
        data = rfile.read(size)
        if len(data) != size:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'connection closed mid-chunk')
        rfile.readline(8)               # chunk's trailing CRLF
        yield data


class ResponseWriter:
    """Serialized writes onto one connection's ``wfile``.

    The lock matters for live sessions: the device loop streams window
    chunks from a worker thread while the handler thread owns the final
    chunk — interleaved partial writes would corrupt the chunk framing.
    """

    def __init__(self, wfile) -> None:
        self._wfile = wfile
        self._lock = threading.Lock()
        self.started = False
        self._chunked = False

    def _head(self, status: int, headers: Dict[str, str]) -> bytes:
        lines = [f'HTTP/1.1 {status} {_REASONS.get(status, "Unknown")}']
        lines += [f'{k}: {v}' for k, v in headers.items()]
        lines += ['Connection: close', '', '']
        return '\r\n'.join(lines).encode('latin-1')

    def send(self, status: int, body: bytes,
             content_type: str = 'application/json') -> None:
        with self._lock:
            if self.started:
                return
            self.started = True
            self._wfile.write(self._head(status, {
                'Content-Type': content_type,
                'Content-Length': str(len(body))}) + body)
            self._wfile.flush()

    def send_json(self, status: int, obj: Dict[str, Any]) -> None:
        self.send(status, json.dumps(obj).encode('utf-8') + b'\n')

    def start_chunked(self, status: int = 200,
                      content_type: str = 'application/json') -> None:
        with self._lock:
            if self.started:
                return
            self.started = True
            self._chunked = True
            self._wfile.write(self._head(status, {
                'Content-Type': content_type,
                'Transfer-Encoding': 'chunked'}))
            self._wfile.flush()

    def write_chunk(self, data: bytes) -> None:
        if not data:
            return
        with self._lock:
            if not self._chunked:
                raise RuntimeError('start_chunked first')
            self._wfile.write(b'%x\r\n' % len(data) + data + b'\r\n')
            self._wfile.flush()

    def end_chunked(self) -> None:
        with self._lock:
            if not self._chunked:
                return
            self._chunked = False
            self._wfile.write(b'0\r\n\r\n')
            self._wfile.flush()


class HttpServer:
    """Accept loop + bounded handler pool + two-phase drain.

    ``handler(request, response, conn)`` runs on its own thread per
    connection; at ``max_connections`` concurrent handlers, further
    connects get an immediate 503 (shed at the transport, before any
    parsing). Every live connection is tracked so ``finish_drain`` can
    force-close stragglers — an abandoned half-open client never pins a
    handler thread past the serve daemon's drain grace.
    """

    def __init__(self, handler: Callable, host: str = '127.0.0.1',
                 port: int = 0, max_connections: int = 64) -> None:
        self.handler = handler
        self.host, self._port_req = host, int(port)
        self.max_connections = int(max_connections)
        self._sock: Optional[socket.socket] = None
        self._conns: set = set()
        self._lock = threading.Lock()
        self._active = 0
        self._draining = False
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        assert self._sock is not None, 'ingress not started'
        return self._sock.getsockname()[1]

    @property
    def open_connections(self) -> int:
        with self._lock:
            return len(self._conns)

    def start(self) -> 'HttpServer':
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self._port_req))
        self._sock.listen(64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name='ingress-accept', daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return                     # socket closed: draining
            with self._lock:
                if self._draining:
                    reject = 'draining'
                elif self._active >= self.max_connections:
                    reject = 'overloaded'
                else:
                    reject = None
                    self._active += 1
                    self._conns.add(conn)
            if reject is not None:
                threading.Thread(target=self._reject,
                                 args=(conn, reject), daemon=True).start()
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name='ingress-conn', daemon=True).start()

    def _reject(self, conn: socket.socket,
                reason: str = 'overloaded') -> None:
        """503 with an honest reason: 'overloaded' (retry with backoff)
        vs 'draining' (fail over — this process is exiting; a client
        retrying against it is wasting its own deadline)."""
        try:
            with conn:
                message = ('server is draining; fail over'
                           if reason == 'draining'
                           else 'connection limit reached; retry with '
                                'backoff')
                body = json.dumps({
                    'ok': False, 'error': reason,
                    'message': message}).encode() + b'\n'
                conn.sendall(
                    b'HTTP/1.1 503 Service Unavailable\r\n'
                    b'Content-Type: application/json\r\n'
                    b'Content-Length: %d\r\nConnection: close\r\n\r\n'
                    % len(body) + body)
        except OSError:
            pass

    # no byte read/written for this long → the connection is torn down
    # (slowloris guard: a silent client must not pin a handler slot —
    # the live endpoint RAISES the timeout after auth, it never waives
    # it)
    READ_TIMEOUT_S = 30.0

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(self.READ_TIMEOUT_S)
                rfile = conn.makefile('rb')
                wfile = conn.makefile('wb')
                resp = ResponseWriter(wfile)
                try:
                    req = read_request(rfile)
                    if req is not None:
                        self.handler(req, resp, conn)
                except HttpError as e:
                    # transport-level rejection (413/400/…): structured
                    # body, never a dropped connection mid-parse
                    try:
                        resp.send_json(e.status, e.body())
                    except (OSError, ValueError):
                        pass
                except (OSError, ValueError, ConnectionError):
                    pass                   # client went away
                # vft-lint: ok=swallowed-exception — reported to the
                # CLIENT as a structured 500 carrying the error; the
                # connection loop must survive one handler's crash
                except Exception as e:
                    try:
                        resp.send_json(INTERNAL_ERROR, {
                            'ok': False, 'error': 'internal',
                            'message': f'{type(e).__name__}: {e}'})
                    except (OSError, ValueError):
                        pass
        finally:
            with self._lock:
                self._active -= 1
                self._conns.discard(conn)

    # -- drain ---------------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop accepting; in-flight handlers keep running."""
        with self._lock:
            self._draining = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def finish_drain(self, grace_s: float = 5.0) -> None:
        """Force-close every connection still open after ``grace_s`` —
        the half-open-reap: a client that vanished mid-request (or never
        finished its live stream) must not pin a handler thread."""
        import time
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._conns:
                    return
            time.sleep(0.05)
        with self._lock:
            stragglers = list(self._conns)
        for conn in stragglers:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
