"""Per-tenant quotas: token-bucket rate limits + concurrent requests.

Two independent gates, both checked BEFORE the serve admission gate so
a quota-shed request never touches — let alone occupies — an admission
slot:

  * the token bucket bounds sustained request RATE (``rate_rps`` tokens
    per second, ``burst`` capacity): classic leaky-bucket arithmetic,
    refilled lazily on each acquire, no timers;
  * the concurrency gate bounds how many of a tenant's extraction
    requests (live sessions included) are IN FLIGHT at once — acquired
    at submit, released when the request reaches a terminal state (the
    gateway listens on ``ExtractionServer.completion_listeners``).

Thread safety: one lock per tenant record; the acquire path is a few
float ops. The manager's snapshot feeds the serve metrics document's
``ingress.tenants`` section.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from video_features_tpu.ingress.auth import Tenant


class TokenBucket:
    """Lazy-refill token bucket. ``rate=None`` = unlimited."""

    def __init__(self, rate: Optional[float], burst: float) -> None:
        self.rate = rate
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t_last = time.monotonic()

    def try_acquire(self, n: float = 1.0) -> bool:
        if self.rate is None:
            return True
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t_last) * self.rate)
        self._t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class _TenantState:
    __slots__ = ('tenant', 'bucket', 'inflight', 'lock',
                 'requests', 'shed')

    def __init__(self, tenant: Tenant) -> None:
        self.tenant = tenant
        self.bucket = TokenBucket(tenant.rate_rps, tenant.burst)
        self.inflight = 0
        self.lock = threading.Lock()
        self.requests = 0          # accepted
        self.shed = 0              # rejected by either gate


class QuotaManager:
    """All tenants' quota state, keyed by tenant name (several API keys
    may map onto one tenant and share its budget)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: Dict[str, _TenantState] = {}

    def _state(self, tenant: Tenant) -> _TenantState:
        with self._lock:
            st = self._states.get(tenant.name)
            if st is None:
                st = self._states[tenant.name] = _TenantState(tenant)
            return st

    def acquire(self, tenant: Tenant) -> Tuple[bool, Optional[str]]:
        """(admitted, shed_reason): ``rate_limited`` when the bucket is
        dry, ``concurrency`` when the tenant's in-flight budget is
        spent. On success the caller OWNS one concurrency unit until
        :meth:`release`."""
        st = self._state(tenant)
        with st.lock:
            # concurrency BEFORE the bucket: a concurrency shed must not
            # debit a rate token, or retries against a full in-flight
            # budget would starve the tenant's rate budget too
            limit = tenant.max_concurrent
            if limit is not None and st.inflight >= limit:
                st.shed += 1
                return False, 'concurrency'
            if not st.bucket.try_acquire():
                st.shed += 1
                return False, 'rate_limited'
            st.inflight += 1
            st.requests += 1
            return True, None

    def release(self, tenant_name: str) -> None:
        with self._lock:
            st = self._states.get(tenant_name)
        if st is None:
            return
        with st.lock:
            st.inflight = max(0, st.inflight - 1)

    def count_shed(self, tenant: Tenant) -> None:
        """Record a shed that happened DOWNSTREAM of the quota gates
        (priority-class admission rejection) against the tenant."""
        st = self._state(tenant)
        with st.lock:
            st.shed += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            states = list(self._states.values())
        out: Dict[str, Dict[str, float]] = {}
        for st in states:
            with st.lock:
                out[st.tenant.name] = {
                    'priority': st.tenant.priority,
                    'inflight': st.inflight,
                    'requests': st.requests,
                    'shed': st.shed,
                }
        return out
