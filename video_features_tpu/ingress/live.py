"""Live sessions: frames arrive over the network, features stream back.

A live session is ONE long-lived ingress request: the client streams
raw frames up in HTTP chunks, the session windows them to the serving
extractor's exact packed geometry (``BaseExtractor.live_window_spec`` —
the same stack/step/host-transform the file path applies), and every
scattered feature row streams back DOWN the same response as its own
chunk, the moment the device loop materializes it. On the scheduler
side the session is just another packed task: its windows pack into the
same device batches as file-backed requests, lulls in frame arrival
surface as FLUSH (partial pools drain, the async loop materializes, the
client sees its windows instead of waiting on future frames), and the
per-video fault-isolation contract holds — a dead client fails exactly
its own session.

Threading: the HANDLER thread reads body chunks and ``push``es frame
batches (bounded queue → TCP backpressure on a fast client); the
DECODE thread runs :meth:`windows`; the DEVICE-LOOP sync thread calls
:meth:`send_window`. ``abort``/generator-close tie the three together
so no thread outlives the session.
"""
from __future__ import annotations

import json
import queue
import threading
from typing import Any, Dict, Optional

import numpy as np

_END = object()


class LiveSessionError(RuntimeError):
    pass


# default cap on RAW frame bytes buffered per session between the
# network reader and the windower: a client outpacing extraction stalls
# in push() (TCP backpressure) instead of growing the daemon's RSS — a
# count-based bound alone would admit queue_batches × max-chunk bytes
LIVE_BUFFER_BYTES = 64 << 20


class LiveSession:
    """State + plumbing for one live extraction session."""

    def __init__(self, session_id: str, tenant: str,
                 fps: float = 25.0, idle_flush_s: float = 0.05,
                 queue_batches: int = 32,
                 max_buffer_bytes: int = LIVE_BUFFER_BYTES) -> None:
        self.id = str(session_id)
        self.tenant = tenant
        self.fps = float(fps)
        if self.fps <= 0:
            raise LiveSessionError(f'fps must be > 0; got {fps}')
        self.idle_flush_s = float(idle_flush_s)
        # the scheduler-facing identity: a pseudo-path (nothing exists
        # at it; the task is ephemeral so resume/cache never stat it)
        self.pseudo_path = f'live-{self.id}.live'
        self._q: 'queue.Queue' = queue.Queue(maxsize=max(queue_batches, 1))
        self.max_buffer_bytes = int(max_buffer_bytes)
        self._buf_bytes = 0                # raw frame bytes queued
        self._buf_cv = threading.Condition()
        self._aborted = threading.Event()
        self._input_done = False
        self.done = threading.Event()      # request reached terminal state
        self.request = None                # bound at admission
        self.windows_in = 0                # windows formed from frames
        self.frames_in = 0
        self.windows_streamed = 0          # feature chunks sent back
        self._writer = None                # ingress.http.ResponseWriter
        self._send_lock = threading.Lock()

    # -- admission-side hooks (serve/server.py) ------------------------------

    def bind(self, request) -> None:
        self.request = request

    def attach_writer(self, writer) -> None:
        self._writer = writer

    # -- input side (handler thread) -----------------------------------------

    def push(self, frames: np.ndarray) -> None:
        """Queue one (N, H, W, 3) uint8 frame batch; blocks when the
        session's buffer is full — bounded in BYTES (max_buffer_bytes),
        not just batch count, so backpressure reaches a fast client
        through TCP before the daemon's memory does. Drops silently
        after an abort — the reader drains the wire so the response can
        still flush."""
        nb = int(frames.nbytes)
        self.frames_in += int(len(frames))
        with self._buf_cv:
            # _buf_bytes > 0 guarantees progress for a single batch
            # larger than the whole budget
            while (self._buf_bytes + nb > self.max_buffer_bytes
                   and self._buf_bytes > 0
                   and not self._aborted.is_set()):
                self._buf_cv.wait(0.1)
            if self._aborted.is_set():
                return
            self._buf_bytes += nb
        while not self._aborted.is_set():
            try:
                self._q.put(frames, timeout=0.1)
                return
            except queue.Full:
                continue
        with self._buf_cv:                 # aborted before enqueue
            self._buf_bytes -= nb
            self._buf_cv.notify_all()

    def end_input(self) -> None:
        """The client finished streaming (zero-length chunk): remaining
        buffered windows flush, then the session's task exhausts."""
        if self._input_done:
            return
        self._input_done = True
        while not self._aborted.is_set():
            try:
                self._q.put(_END, timeout=0.1)
                return
            except queue.Full:
                continue

    def abort(self) -> None:
        """Tear the session down (client vanished, server drain): the
        window generator ends, push() stops blocking, and whatever was
        already computed still streams/finalizes."""
        self._aborted.set()
        with self._buf_cv:
            self._buf_cv.notify_all()      # unblock byte-budget waiters
        try:
            self._q.put_nowait(_END)
        except queue.Full:
            pass

    @property
    def aborted(self) -> bool:
        return self._aborted.is_set()

    # -- decode-side window source (runs on the packed decode thread) --------

    def _frame_batches(self, transform):
        """Transformed frame batches off the network queue, with FLUSH
        on every ``idle_flush_s`` lull; ends at end-of-input/abort."""
        from video_features_tpu.parallel.packing import FLUSH
        while not self._aborted.is_set():
            try:
                item = self._q.get(timeout=self.idle_flush_s)
            except queue.Empty:
                yield FLUSH
                continue
            if item is _END:
                return
            with self._buf_cv:             # raw bytes left the queue
                self._buf_bytes -= int(item.nbytes)
                self._buf_cv.notify_all()
            yield [np.asarray(transform(f) if transform is not None
                              else f) for f in item]

    def windows(self, ex):
        """The task's ``windows_override``: replay the extractor's exact
        packed windowing over the network frame stream. Yields
        ``(window, meta)`` plus FLUSH on arrival lulls (every
        ``idle_flush_s`` without frames), so pooled windows never wait
        on future traffic. Stack families run through THE SAME
        ``stream_windows`` the file-backed path uses (FLUSH passes
        through it), so live and decoded windowing cannot diverge."""
        from video_features_tpu.extract.streaming import stream_windows
        from video_features_tpu.parallel.packing import FLUSH
        spec = ex.live_window_spec()
        if spec is None:
            raise LiveSessionError(
                f'{getattr(ex, "feature_type", type(ex).__name__)} does '
                'not support live sessions')
        win, step, transform, timed = spec
        try:
            if timed:
                # frame-wise families: window == frame, meta is the
                # timestamp at the session's declared fps
                idx = 0
                for item in self._frame_batches(transform):
                    if item is FLUSH:
                        yield FLUSH
                        continue
                    for f in item:
                        self.windows_in += 1
                        yield f, idx / self.fps * 1000.0
                        idx += 1
                return

            def loader_protocol():
                # (batch, times, indices) shape stream_windows consumes;
                # FLUSH items ride through bare
                for item in self._frame_batches(transform):
                    yield item if item is FLUSH else (item, None, None)

            for w in stream_windows(loader_protocol(), win, step):
                if w is FLUSH:
                    yield FLUSH
                    continue
                self.windows_in += 1
                yield w, None
        except BaseException:
            # abnormal end ONLY (the scheduler failed/closed the task,
            # an exception mid-windowing): tear the session down so a
            # reader blocked in push() unblocks. A NORMAL end-of-input
            # must NOT abort — windows still pooled in the packer when
            # the client sends its terminator have yet to stream back
            # through send_window, and aborting here would drop them
            # (and fail the task) on every no-idle-lull session.
            self._aborted.set()
            raise

    # -- output side (device-loop sync thread) --------------------------------

    def send_window(self, feats: Dict[str, Any], meta) -> None:
        """Stream one scattered feature row to the client as a chunk:
        one JSON line ``{"window": k, "feats": {key: [floats]}}`` (+
        ``timestamp_ms`` for frame-wise families). Raises on a dead
        client — the scheduler then fails the task, which stops decode
        and ends the session."""
        writer = self._writer
        if writer is None or self._aborted.is_set():
            raise LiveSessionError('live session has no live client')
        row: Dict[str, Any] = {
            'window': self.windows_streamed,
            'feats': {k: np.asarray(v).tolist() for k, v in feats.items()},
        }
        if meta is not None:
            row['timestamp_ms'] = float(meta)
        payload = (json.dumps(row) + '\n').encode('utf-8')
        with self._send_lock:
            writer.write_chunk(payload)
            self.windows_streamed += 1


def decode_frame_chunk(data: bytes, max_frames: int = 1024) -> np.ndarray:
    """One client frame chunk → a (N, H, W, 3) uint8 batch.

    The wire format is a serialized ``.npy`` (``np.save`` bytes,
    ``allow_pickle=False`` — never unpickle network input) holding
    either one HWC frame or an NHWC batch.
    """
    import io
    try:
        arr = np.load(io.BytesIO(data), allow_pickle=False)
    except Exception as e:
        raise LiveSessionError(f'undecodable frame chunk ({e}); frames '
                               'must be .npy-serialized uint8 arrays')
    if arr.ndim == 3:
        arr = arr[None]
    if arr.ndim != 4 or arr.shape[-1] != 3:
        raise LiveSessionError(
            f'frames must be (H, W, 3) or (N, H, W, 3); got {arr.shape}')
    if arr.dtype != np.uint8:
        raise LiveSessionError(f'frames must be uint8; got {arr.dtype}')
    if len(arr) > max_frames:
        raise LiveSessionError(
            f'frame chunk of {len(arr)} frames exceeds {max_frames}')
    return arr
