"""The ingress gateway: HTTP routes glued to the extraction server.

Endpoints (all JSON unless noted; auth via ``Authorization: Bearer
<key>`` or ``X-API-Key``):

  * ``GET  /healthz``            — liveness (NO auth: load balancers)
  * ``GET  /v1/metrics``         — the serve metrics document
  * ``GET  /metrics``            — Prometheus text exposition 0.0.4
    (the fleet router's HTTP front door serves the same route with the
    FLEET-aggregated exposition — host-relabeled backend families +
    ``vft_fleet_*``/``vft_slo_*``; see docs/fleet.md)
  * ``POST /v1/extract``         — submit an extraction request
    (``{feature_type, video_paths, overrides?, timeout_s?,
    range?: [start_s, end_s], priority?, features?: [..]}``) →
    ``{request_id, tenant, trace_id}``. A W3C ``traceparent`` request
    header joins the request to the caller's distributed trace; minted
    when absent/malformed. ``features`` (wire v1.2) submits a FUSED
    multi-family request: the response carries the umbrella request_id
    plus a per-family ``requests`` id map (and ``errors`` for families
    rejected mid-fan-out); status on the umbrella id nests per-family
    ``videos``. One quota unit per fused request, not per family.
  * ``POST /v1/search``          — query the feature index (wire v1.3;
    requires ``index_enabled``). By vector: ``{family, vector: [..],
    k?}`` → ``{hits: [..]}``; by video: ``{video_path, features: [..],
    k?, timeout_s?}`` (extracts through the fused path, waits for
    ingest, queries with the video's own windows) → ``{results:
    {family: [hits]}}``. Quota-gated like extract; the query holds its
    tenant's concurrency unit only while it runs.
  * ``GET  /v1/requests/<id>``   — request status (tenant-scoped)
  * ``GET  /v1/requests/<id>/trace`` — the request's assembled span
    timeline (tenant-scoped: ANOTHER tenant's id answers 403 — the
    trace surface is explicit about authorization, unlike status's
    deliberate 404 ambiguity, because traces carry video paths and
    config detail worth a loud denial)
  * ``POST /v1/live/<session>``  — live session: chunked request body
    (first chunk: JSON header ``{feature_type, fps?, overrides?,
    timeout_s?, priority?}``; then ``.npy`` frame batches; empty chunk
    ends), chunked response (one JSON line per extracted window, then a
    final ``{"done": true, ...}`` line).

Admission layering — each gate sheds BEFORE the next spends anything:

  1. transport: connection cap (503), body/header bounds (413/431);
  2. auth: unknown key → 401, before the body is read;
  3. quota: per-tenant token bucket + concurrent-request budget (429);
  4. serve admission: queue depth by PRIORITY CLASS — a saturated queue
     sheds ``batch`` before ``interactive`` (503 ``queue_full``).

A shed request never occupies an admission slot, and every shed
increments ``vft_ingress_shed_total{tenant, class, reason}``.
"""
from __future__ import annotations

import json
import socket
import time
from collections import deque
from threading import Lock
from typing import Any, Dict, Optional, Tuple

from video_features_tpu.ingress.auth import ApiKeyAuth, Tenant
from video_features_tpu.ingress.http import (
    BAD_REQUEST, CLIENT_CLOSED, CONFLICT, FORBIDDEN, INTERNAL_ERROR,
    METHOD_NOT_ALLOWED, NOT_FOUND, OK, SERVICE_UNAVAILABLE,
    TOO_MANY_REQUESTS, UNAUTHORIZED, HttpError, HttpRequest, HttpServer,
    ResponseWriter,
)
from video_features_tpu.ingress.live import (
    LiveSession, LiveSessionError, decode_frame_chunk,
)
from video_features_tpu.ingress.quota import QuotaManager

# request_id → tenant retention (status scoping + quota release); same
# bound as the server's own request history
OWNER_HISTORY = 4096

# a live session whose client stops sending/reading for this long is
# torn down (half-open protection between drains)
LIVE_IDLE_TIMEOUT_S = 300.0
# after the client finishes its frames, how long to wait for the device
# loop to finalize before answering with the current state
LIVE_FINALIZE_TIMEOUT_S = 300.0

_EXTRACT_FIELDS = frozenset({'feature_type', 'video_paths', 'overrides',
                             'timeout_s', 'range', 'priority', 'features'})
_LIVE_FIELDS = frozenset({'feature_type', 'fps', 'overrides', 'timeout_s',
                          'priority'})
_SEARCH_FIELDS = frozenset({'family', 'vector', 'video_path', 'features',
                            'k', 'timeout_s', 'priority'})

# W3C Trace Context request header (lowercased by the header parser)
_TRACEPARENT_HEADER = 'traceparent'


class IngressGateway:
    """One network front door over one :class:`ExtractionServer`."""

    def __init__(self, server, host: str = '127.0.0.1', port: int = 0,
                 auth_file: Optional[str] = None,
                 auth: Optional[ApiKeyAuth] = None,
                 max_body_bytes: int = 64 * (1 << 20),
                 max_connections: int = 64) -> None:
        if auth is None:
            if not auth_file:
                raise ValueError('the ingress requires an API-key file '
                                 '(serve_ingress_auth_file)')
            auth = ApiKeyAuth.from_file(auth_file)
        self.server = server
        self.auth = auth
        self.quota = QuotaManager()
        self.max_body_bytes = int(max_body_bytes)
        self.http = HttpServer(self._handle, host=host, port=port,
                               max_connections=max_connections)
        self._lock = Lock()
        # status-scoping table (request_id → tenant), aged out at
        # OWNER_HISTORY — but never while the request still holds a
        # concurrency unit (see _pending_release)
        self._owners: Dict[str, str] = {}
        self._owner_order: 'deque[str]' = deque()
        # the QUOTA ledger, separate from status scoping: request_id →
        # tenant for every request still holding a concurrency unit.
        # Entries leave ONLY on completion, so history aging can never
        # leak a unit (a live session outliving 4096 newer requests
        # would otherwise lock its tenant out forever); size is bounded
        # by admission (queue depth + live sessions), not by history.
        self._pending_release: Dict[str, str] = {}
        # completions that beat _own() to the punch (an all-cache-hit
        # request is terminal INSIDE submit, before the gateway learns
        # its id): _own() settles these immediately instead of leaking
        # the tenant's concurrency unit. BOUNDED: every loopback
        # request's completion also lands here (the gateway never owns
        # those), and the race window this covers is microseconds.
        self._early_done: 'deque[str]' = deque(maxlen=256)
        self._live: Dict[str, LiveSession] = {}  # session_id → session
        self._live_by_request: Dict[str, LiveSession] = {}
        self._requests_total = 0
        self._shed_total = 0
        self._recorder = None                   # ingress spans (trace_out)
        # instruments live on the SERVER's registry so one scrape (the
        # loopback metrics_prom command, the .prom mirror, GET /metrics)
        # carries serve + ingress families together
        reg = server.registry
        self._g_live = reg.gauge(
            'vft_ingress_live_sessions', 'live sessions in flight')
        self._g_conns = reg.gauge(
            'vft_ingress_open_connections', 'open ingress connections')
        self._h_latency = reg.histogram(
            'vft_ingress_request_latency_seconds',
            'ingress request handling latency (headers to response end)')
        self._reg = reg

    # -- lifecycle -----------------------------------------------------------

    @property
    def host(self) -> str:
        return self.http.host

    @property
    def port(self) -> int:
        return self.http.port

    @property
    def n_tenants(self) -> int:
        return self.auth.n_tenants

    def start(self) -> 'IngressGateway':
        trace_out = self.server.base_overrides.get('trace_out')
        if trace_out:
            # ingress spans join the server-wide merged Perfetto export
            # — on the PERSISTENT list: warm-pool churn ages out worker
            # recorders, never the front door's
            from video_features_tpu.obs.spans import SpanRecorder
            self._recorder = SpanRecorder()
            self.server._persistent_recorders.append(self._recorder)
        self.http.start()
        self.server.attach_ingress(self)
        self.server.completion_listeners.append(self._on_request_done)
        return self

    def begin_drain(self) -> None:
        """Serve-drain phase 1: stop accepting, end every live session's
        frame input (their tasks finish with the frames already queued,
        so the warm workers' feeds can actually drain)."""
        self.http.begin_drain()
        with self._lock:
            sessions = list(self._live.values())
        for s in sessions:
            s.end_input()

    def finish_drain(self, grace_s: float = 5.0) -> None:
        """Serve-drain phase 2 (after workers joined): abort whatever
        sessions remain and force-close half-open connections — no
        vanished client pins a handler thread or a warm-pool entry."""
        with self._lock:
            sessions = list(self._live.values())
        for s in sessions:
            s.abort()
        self.http.finish_drain(grace_s)

    # -- metrics -------------------------------------------------------------

    def _count(self, endpoint: str, tenant: Optional[str],
               status: int) -> None:
        self._reg.counter(
            'vft_ingress_requests_total',
            'ingress requests by tenant, endpoint, and status code',
            labels={'tenant': tenant or 'anonymous', 'endpoint': endpoint,
                    'code': str(status)}).inc()
        with self._lock:
            self._requests_total += 1

    def _count_shed(self, tenant: Tenant, priority: str,
                    reason: str) -> None:
        self._reg.counter(
            'vft_ingress_shed_total',
            'ingress requests shed before occupying an admission slot, '
            'by tenant, priority class, and reason',
            labels={'tenant': tenant.name, 'class': priority,
                    'reason': reason}).inc()
        with self._lock:
            self._shed_total += 1

    def stats(self) -> Dict[str, Any]:
        """The serve metrics document's ``ingress`` section."""
        with self._lock:
            live = len(self._live)
            requests_total = self._requests_total
            shed_total = self._shed_total
        conns = self.http.open_connections
        self._g_live.set(live)
        self._g_conns.set(conns)
        return {'enabled': True,
                'requests_total': requests_total,
                'shed_total': shed_total,
                'live_sessions': live,
                'open_connections': conns,
                'tenants': self.quota.snapshot()}

    # -- completion plumbing -------------------------------------------------

    def _on_request_done(self, req) -> None:
        """Server completion listener: release the owning tenant's
        concurrency unit; wake the live handler waiting on this id."""
        with self._lock:
            tenant_name = self._pending_release.pop(req.id, None)
            session = self._live_by_request.pop(req.id, None)
            if tenant_name is None:
                # completed before _own() ran (terminal-at-birth cache
                # hit): settle when the submitter records ownership.
                # (Loopback-submitted requests land here too and are
                # never claimed — the deque's maxlen ages them out.)
                self._early_done.append(req.id)
        if tenant_name is not None:
            self.quota.release(tenant_name)
        if session is not None:
            # terminal means no more windows will ever be consumed:
            # abort the input side too, so a handler blocked pushing
            # frames against a full queue (expired deadline, worker
            # crash) unblocks instead of deadlocking until the client
            # gives up
            session.abort()
            session.done.set()

    def _own(self, request_id: str, tenant: Tenant) -> None:
        early = False
        with self._lock:
            if request_id in self._early_done:
                # lost the race with completion: the unit is released
                # below, never ledgered
                early = True
                try:
                    self._early_done.remove(request_id)
                except ValueError:
                    pass
            else:
                self._pending_release[request_id] = tenant.name
            self._owners[request_id] = tenant.name
            self._owner_order.append(request_id)
            # age out TERMINAL requests only; still-running ones (in the
            # quota ledger) keep their status scoping — rotation is
            # bounded because running requests are bounded by admission
            scans = len(self._owner_order)
            while len(self._owner_order) > OWNER_HISTORY and scans > 0:
                scans -= 1
                old = self._owner_order.popleft()
                if old in self._pending_release:
                    self._owner_order.append(old)
                else:
                    self._owners.pop(old, None)
        if early:
            self.quota.release(tenant.name)

    # -- routing -------------------------------------------------------------

    def _handle(self, req: HttpRequest, resp: ResponseWriter,
                conn: socket.socket) -> None:
        t0 = time.perf_counter()
        endpoint = self._endpoint_label(req)
        tenant: Optional[Tenant] = None
        status = INTERNAL_ERROR
        request_id = None
        try:
            if req.path == '/healthz':
                status = OK
                resp.send_json(OK, {
                    'ok': True, 'draining': self.server._draining})
                return
            tenant = self.auth.authenticate(req.headers)
            if tenant is None:
                status = UNAUTHORIZED
                resp.send_json(UNAUTHORIZED, {
                    'ok': False, 'error': 'unauthorized',
                    'message': 'missing or unknown API key '
                               '(Authorization: Bearer <key>)'})
                return
            status, request_id = self._route(req, resp, conn, tenant)
        except HttpError as e:
            status = e.status
            body = e.body()
            if tenant is not None:
                body.setdefault('tenant', tenant.name)
            try:
                resp.send_json(e.status, body)
            except (OSError, ValueError):
                pass
        except (OSError, ConnectionError, socket.timeout):
            status = CLIENT_CLOSED            # client went away mid-request
        finally:
            dt = time.perf_counter() - t0
            self._h_latency.observe(dt)
            self._count(endpoint, tenant.name if tenant else None, status)
            if self._recorder is not None:
                attrs = dict(endpoint=endpoint,
                             tenant=(tenant.name if tenant else None),
                             status=status, request_id=request_id)
                trace_id = self._trace_id_of(request_id)
                if trace_id is not None:
                    # the ingress hop is its own span under the
                    # request's trace (span_id pairs with trace_id —
                    # tools/trace_view.py validates the pairing)
                    from video_features_tpu.obs.context import \
                        new_span_id
                    attrs.update(trace_id=trace_id,
                                 span_id=new_span_id())
                self._recorder.span('ingress', t0, t0 + dt, **attrs)

    def _trace_id_of(self, request_id: Optional[str]) -> Optional[str]:
        """The trace id a (possibly just-admitted) request carries, or
        None — same internal-seam access the drain plumbing uses."""
        if request_id is None:
            return None
        with self.server._lock:
            req = self.server._requests.get(request_id)
        trace = getattr(req, 'trace', None)
        return trace.trace_id if trace is not None else None

    @staticmethod
    def _endpoint_label(req: HttpRequest) -> str:
        """Low-cardinality endpoint label: ids stripped, and UNKNOWN
        paths collapse to 'other' — the label feeds a Prometheus family
        whose series are never evicted, so an unauthenticated port sweep
        over arbitrary paths must not mint a series per path."""
        p = req.path
        if p in ('/healthz', '/metrics', '/v1/metrics', '/v1/extract',
                 '/v1/search'):
            return p
        if p.startswith('/v1/requests/'):
            return ('/v1/requests/trace' if p.endswith('/trace')
                    else '/v1/requests')
        if p.startswith('/v1/live/'):
            return '/v1/live'
        return 'other'

    def _route(self, req: HttpRequest, resp: ResponseWriter,
               conn: socket.socket,
               tenant: Tenant) -> Tuple[int, Optional[str]]:
        path, method = req.path, req.method
        if path == '/v1/metrics' and method == 'GET':
            resp.send_json(OK, {'ok': True,
                                'metrics': self.server.metrics()})
            return OK, None
        if path == '/metrics' and method == 'GET':
            text = self.server._prometheus(self.server.metrics())
            resp.send(OK, text.encode('utf-8'),
                      content_type='text/plain; version=0.0.4')
            return OK, None
        if path == '/v1/extract' and method == 'POST':
            return self._handle_extract(req, resp, tenant)
        if path == '/v1/search' and method == 'POST':
            return self._handle_search(req, resp, tenant)
        if path.startswith('/v1/requests/') and path.endswith('/trace') \
                and method == 'GET':
            return self._handle_trace(req, resp, tenant)
        if path.startswith('/v1/requests/') and method == 'GET':
            return self._handle_status(req, resp, tenant)
        if path.startswith('/v1/live/') and method == 'POST':
            return self._handle_live(req, resp, conn, tenant)
        raise HttpError(NOT_FOUND if method in ('GET', 'POST')
                        else METHOD_NOT_ALLOWED,
                        'not_found', f'no route {method} {path}')

    # -- extraction requests --------------------------------------------------

    def _resolve_priority(self, body: Dict[str, Any],
                          tenant: Tenant) -> str:
        from video_features_tpu.serve.protocol import PRIORITIES
        priority = body.get('priority') or tenant.priority
        if priority not in PRIORITIES:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'unknown priority {priority!r}; known: '
                            f'{", ".join(PRIORITIES)}')
        if priority == 'interactive' and tenant.priority == 'batch':
            # the key's class is a CAP, not a default: an operator
            # provisions a batch key precisely so saturation sheds it
            # first — a client-side header must not reclaim the
            # interactive headroom that policy protects
            raise HttpError(FORBIDDEN, 'priority_forbidden',
                            f'tenant {tenant.name!r} is provisioned as '
                            "'batch' and cannot request 'interactive'",
                            tenant=tenant.name)
        return priority

    def _check_quota(self, tenant: Tenant, priority: str) -> None:
        ok, reason = self.quota.acquire(tenant)
        if not ok:
            self._count_shed(tenant, priority, reason)
            raise HttpError(
                TOO_MANY_REQUESTS, reason,
                f'tenant {tenant.name!r} is over its '
                + ('request rate' if reason == 'rate_limited'
                   else 'concurrent-request budget'),
                tenant=tenant.name, request_id=None)

    def _submit_error(self, result: Dict[str, Any], tenant: Tenant,
                      priority: str) -> HttpError:
        """Map a serve-side rejection onto a structured HTTP error; a
        queue_full rejection is a SHED (it never occupied a slot)."""
        err = result.get('error', 'rejected')
        if err == 'queue_full':
            self._count_shed(tenant, priority, 'queue_full')
            self.quota.count_shed(tenant)
            return HttpError(SERVICE_UNAVAILABLE, 'queue_full',
                             'admission queue is full for priority '
                             f'class {priority!r}; retry with backoff',
                             tenant=tenant.name, priority=priority,
                             depth=result.get('depth'),
                             capacity=result.get('capacity'))
        if err == 'draining':
            return HttpError(SERVICE_UNAVAILABLE, 'draining', 'server is draining',
                             tenant=tenant.name)
        return HttpError(BAD_REQUEST, 'rejected', str(err), tenant=tenant.name)

    def _handle_extract(self, req: HttpRequest, resp: ResponseWriter,
                        tenant: Tenant) -> Tuple[int, Optional[str]]:
        body = req.json_body(self.max_body_bytes)
        unknown = set(body) - _EXTRACT_FIELDS
        if unknown:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'unknown fields: {sorted(unknown)}')
        priority = self._resolve_priority(body, tenant)
        self._check_quota(tenant, priority)
        try:
            result = self.server.submit(
                body.get('feature_type'), body.get('video_paths'),
                overrides=body.get('overrides'),
                timeout_s=body.get('timeout_s'),
                range_s=body.get('range'), priority=priority,
                traceparent=req.headers.get(_TRACEPARENT_HEADER),
                features=body.get('features'))
        except Exception:
            self.quota.release(tenant.name)
            raise
        if not result.get('ok'):
            self.quota.release(tenant.name)
            raise self._submit_error(result, tenant, priority)
        rid = result['request_id']
        self._own(rid, tenant)
        out = {'ok': True, 'request_id': rid,
               'tenant': tenant.name, 'priority': priority,
               'trace_id': result.get('trace_id')}
        # fused submits answer with the per-family child-id map (and any
        # families rejected mid-fan-out) alongside the umbrella id
        for k in ('requests', 'errors'):
            if k in result:
                out[k] = result[k]
        resp.send_json(OK, out)
        return OK, rid

    def _handle_search(self, req: HttpRequest, resp: ResponseWriter,
                       tenant: Tenant) -> Tuple[int, Optional[str]]:
        """``POST /v1/search`` — the feature-index query surface.
        Same admission layering as extract (auth happened upstream;
        priority cap, then quota) but the concurrency unit is held only
        for the synchronous query, released in ``finally`` — there is
        no completion listener to wait on."""
        body = req.json_body(self.max_body_bytes)
        unknown = set(body) - _SEARCH_FIELDS
        if unknown:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'unknown fields: {sorted(unknown)}')
        svc = self.server.index_service
        if svc is None:
            # shed before admission: a disabled index never spends a
            # quota unit
            raise HttpError(SERVICE_UNAVAILABLE, 'index_disabled',
                            'the feature index is not enabled on this '
                            'server (index_enabled=true)',
                            tenant=tenant.name)
        priority = self._resolve_priority(body, tenant)
        self._check_quota(tenant, priority)
        try:
            if body.get('video_path') is not None:
                result = svc.search_by_video(
                    body['video_path'], features=body.get('features'),
                    k=int(body.get('k', 10)),
                    timeout_s=body.get('timeout_s'), priority=priority,
                    traceparent=req.headers.get(_TRACEPARENT_HEADER))
            else:
                result = svc.search_vector(
                    body.get('family'), body.get('vector'),
                    k=int(body.get('k', 10)))
        except (TypeError, ValueError, KeyError) as e:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'search failed: {e}', tenant=tenant.name)
        finally:
            self.quota.release(tenant.name)
        rid = result.get('request_id')
        if not result.get('ok'):
            raise HttpError(BAD_REQUEST, 'search_failed',
                            str(result.get('error', 'search failed')),
                            tenant=tenant.name, request_id=rid)
        result.pop('ok', None)
        resp.send_json(OK, {'ok': True, 'tenant': tenant.name, **result})
        return OK, rid

    def _handle_trace(self, req: HttpRequest, resp: ResponseWriter,
                      tenant: Tenant) -> Tuple[int, Optional[str]]:
        """``GET /v1/requests/<id>/trace`` — one request's assembled
        span timeline. Tenant-scoped with an EXPLICIT 403 on a foreign
        id (unlike status's 404 ambiguity): traces carry video paths,
        stage timings, and config detail, so a cross-tenant read is an
        authorization failure worth naming. Known tradeoff: with the
        sequential r%06d id space this distinguishes "exists, not
        yours" from "never existed" — a deliberate choice of audit
        clarity over id-space opacity on THIS route only (status keeps
        the uniform 404); revisit if ids ever need to be unguessable."""
        rid = req.path[len('/v1/requests/'):-len('/trace')]
        with self._lock:
            owner = self._owners.get(rid)
        if owner is None:
            raise HttpError(NOT_FOUND, 'not_found',
                            f'unknown request_id {rid!r}',
                            tenant=tenant.name, request_id=rid)
        if owner != tenant.name:
            raise HttpError(FORBIDDEN, 'forbidden',
                            f'request {rid!r} belongs to another tenant',
                            tenant=tenant.name, request_id=rid)
        tr = self.server.request_trace(rid)
        if not tr.get('ok'):
            raise HttpError(NOT_FOUND, 'not_found',
                            tr.get('error', f'unknown request {rid!r}'),
                            tenant=tenant.name, request_id=rid)
        tr.pop('ok', None)
        tr['tenant'] = tenant.name
        resp.send_json(OK, {'ok': True, **tr})
        return OK, rid

    def _handle_status(self, req: HttpRequest, resp: ResponseWriter,
                       tenant: Tenant) -> Tuple[int, Optional[str]]:
        rid = req.path[len('/v1/requests/'):]
        with self._lock:
            owner = self._owners.get(rid)
        if owner != tenant.name:
            # someone else's request id is indistinguishable from an
            # unknown one — the id space must not leak across tenants
            raise HttpError(NOT_FOUND, 'not_found',
                            f'unknown request_id {rid!r}',
                            tenant=tenant.name, request_id=rid)
        st = self.server.status(rid)
        if not st.get('ok'):
            raise HttpError(NOT_FOUND, 'not_found',
                            st.get('error', f'unknown request {rid!r}'),
                            tenant=tenant.name, request_id=rid)
        st.pop('ok', None)
        st['tenant'] = tenant.name
        resp.send_json(OK, {'ok': True, **st})
        return OK, rid

    # -- live sessions ---------------------------------------------------------

    def _handle_live(self, req: HttpRequest, resp: ResponseWriter,
                     conn: socket.socket,
                     tenant: Tenant) -> Tuple[int, Optional[str]]:
        sid = req.path[len('/v1/live/'):]
        if not sid or '/' in sid or len(sid) > 128:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'malformed session id {sid!r}')
        chunks = req.iter_chunks(self.max_body_bytes)
        try:
            header_raw = next(chunks)
        except StopIteration:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            'live session body must start with a JSON '
                            'header chunk')
        try:
            header = json.loads(header_raw.decode('utf-8'))
        except (ValueError, UnicodeDecodeError) as e:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'malformed live-session header: {e}')
        unknown = set(header) - _LIVE_FIELDS
        if unknown:
            raise HttpError(BAD_REQUEST, 'bad_request',
                            f'unknown header fields: {sorted(unknown)}')
        priority = self._resolve_priority(header, tenant)
        try:
            session = LiveSession(
                sid, tenant.name, fps=float(header.get('fps', 25.0)),
                idle_flush_s=self.server.idle_flush_s)
        except (LiveSessionError, TypeError, ValueError) as e:
            raise HttpError(BAD_REQUEST, 'bad_request', str(e))

        # duplicate in-flight session ids are REJECTED: two writers on
        # one session id would interleave frames into one window stream
        with self._lock:
            if sid in self._live:
                raise HttpError(
                    CONFLICT, 'duplicate_session',
                    f'live session {sid!r} is already in flight',
                    tenant=tenant.name, session=sid)
            self._live[sid] = session
        self._g_live.set(len(self._live))

        rid = None
        try:
            self._check_quota(tenant, priority)
            released = False
            try:
                session.attach_writer(resp)
                result = self.server.submit_live(
                    header.get('feature_type'), session,
                    overrides=header.get('overrides'),
                    timeout_s=header.get('timeout_s'),
                    priority=priority,
                    traceparent=req.headers.get(_TRACEPARENT_HEADER))
                if not result.get('ok'):
                    released = True
                    self.quota.release(tenant.name)
                    raise self._submit_error(result, tenant, priority)
                rid = result['request_id']
                self._own(rid, tenant)
                with self._lock:
                    self._live_by_request[rid] = session
                st0 = self.server.status(rid)
                if st0.get('ok') and st0.get('state') != 'running':
                    # terminal before we registered (e.g. instant crash
                    # path): abort the input side — no scheduler will
                    # ever drain the frame queue, so a client still
                    # streaming would wedge push() — and skip the
                    # finalize wait below
                    session.abort()
                    session.done.set()
            except BaseException:
                if not released and rid is None:
                    self.quota.release(tenant.name)
                raise

            resp.start_chunked(OK)
            resp.write_chunk((json.dumps(
                {'ok': True, 'request_id': rid, 'session': sid,
                 'tenant': tenant.name}) + '\n').encode('utf-8'))

            # stream frames up; windows stream back concurrently via
            # session.send_window on the device-loop thread
            conn.settimeout(LIVE_IDLE_TIMEOUT_S)
            error: Optional[str] = None
            try:
                for chunk in chunks:
                    session.push(decode_frame_chunk(chunk))
                session.end_input()
            except (HttpError, LiveSessionError) as e:
                error = str(e)
                session.abort()
            except (OSError, ConnectionError, socket.timeout):
                error = 'client stream ended unexpectedly'
                session.abort()

            session.done.wait(LIVE_FINALIZE_TIMEOUT_S)
            st = self.server.status(rid)
            final = {'done': True, 'request_id': rid, 'session': sid,
                     'tenant': tenant.name,
                     'windows': session.windows_streamed,
                     'frames': session.frames_in,
                     'state': st.get('state', 'unknown')}
            if error:
                final['error'] = error
            try:
                resp.write_chunk((json.dumps(final) + '\n')
                                 .encode('utf-8'))
                resp.end_chunked()
            except (OSError, ValueError):
                pass
            return OK, rid
        finally:
            session.abort()
            with self._lock:
                self._live.pop(sid, None)
                if rid is not None:
                    self._live_by_request.pop(rid, None)
            self._g_live.set(len(self._live))
