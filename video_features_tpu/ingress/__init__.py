"""Ingress: the network front door for the warm-pool extraction service.

The loopback JSON-lines socket (``serve/protocol.py``) is a LOCAL
control surface; this package is what external traffic hits. Stdlib-only
HTTP/1.1 (+ chunked transfer both ways), because the container bakes no
HTTP framework and the endpoint needs exactly four things a
hand-rolled transport gives us precise control over: bounded
concurrency, streaming request/response bodies for live sessions,
structured over-limit rejections, and a drain that composes with the
serve daemon's SIGTERM path.

Modules:

  * ``http``    — transport: request framing/validation, chunked
    streaming, bounded-concurrency accept loop, connection reaping;
  * ``auth``    — API-key tenancy: keys file → :class:`auth.Tenant`
    (name, priority class, quota parameters);
  * ``quota``   — per-tenant token-bucket rate limits + concurrent
    request quotas;
  * ``live``    — live sessions: network frames → the extractor's
    window geometry → per-window streamed feature chunks;
  * ``gateway`` — routes + the vft_ingress_* metrics surface, glued to
    :class:`serve.server.ExtractionServer`.
"""
from video_features_tpu.ingress.auth import ApiKeyAuth, Tenant  # noqa: F401
from video_features_tpu.ingress.gateway import IngressGateway  # noqa: F401
from video_features_tpu.ingress.quota import QuotaManager  # noqa: F401
