"""video_features_tpu — a TPU-native video feature-extraction framework.

A from-scratch JAX/XLA/Pallas re-design with the capability surface of
``video_features`` (reference: /root/reference): given video files, run frozen
pretrained models (3D CNNs, optical flow, image backbones, an audio net) over
frame stacks / frames / audio tracks and print or persist per-clip features.

Architecture (TPU-first, not a port):
  * all model compute is batched, fixed-shape, jit-compiled XLA (bf16/f32);
  * host-side decode/preprocess streams fixed-shape NumPy clip tensors into HBM;
  * scale-out = data parallelism over a ``jax.sharding.Mesh`` plus the
    idempotent-output/skip-if-exists contract of the reference
    (reference README.md:70-84, models/_base/base_extractor.py:100-132).

Public API::

    from video_features_tpu import create_extractor, load_config
    args = load_config('i3d', overrides={'video_paths': ['a.mp4']})
    extractor = create_extractor(args)
    feats = extractor.extract('a.mp4')     # {'rgb': (T,1024), 'flow': (T,1024)}
"""

from video_features_tpu.config import load_config, sanity_check, Config
from video_features_tpu.registry import EXTRACTORS, create_extractor

__version__ = '0.1.0'

__all__ = [
    'load_config', 'sanity_check', 'Config', 'EXTRACTORS', 'create_extractor',
    '__version__',
]
