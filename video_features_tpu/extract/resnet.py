"""ResNet frame-wise extractor (reference models/resnet/extract_resnet.py).

Transform parity with torchvision's IMAGENET1K_V1 preset (the reference takes
transforms straight from the weights object, extract_resnet.py:41-44):
short-side resize 256 (host, PIL bilinear/antialiased) → center crop 224 →
scale to [0,1] → normalize — the latter two fused into the jitted step.
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np

from video_features_tpu.extract.framewise import BaseFrameWiseExtractor
from video_features_tpu.models import resnet as resnet_model
from video_features_tpu.ops.transforms import (
    center_crop_host, normalize, short_side_resize_pil, to_float_zero_one,
)
from video_features_tpu.utils.device import jax_device

RESIZE_SIZE = 256
CROP_SIZE = 224
# Per-arch IMAGENET1K_V1 preset deviations (the reference takes transforms
# straight from the torchvision weights object, extract_resnet.py:41-44;
# resnext101_64x4d's V1 recipe is resize_size=232 — every other family
# member's is 256)
RESIZE_OVERRIDES = {'resnext101_64x4d': 232}


class ExtractResNet(BaseFrameWiseExtractor):

    def __init__(self, args) -> None:
        self.model_name = args.model_name
        cfg = resnet_model.ARCHS[self.model_name]
        super().__init__(args, feat_dim=cfg['feat_dim'])
        self._device = jax_device(self.device)
        self.params = jax.device_put(self.load_params(args), self._device)
        # dtype rides the partial as a trace-time constant: the float32
        # lane's jitted program is byte-identical to the pre-knob graph
        self._step = jax.jit(partial(self._forward, arch=self.model_name,
                                     dtype=self.compute_jnp_dtype))

    def load_params(self, args):
        from video_features_tpu.extract.weights import load_or_init
        return load_or_init(
            args, 'checkpoint_path',
            partial(resnet_model.init_state_dict, arch=self.model_name),
            feature_type='resnet', what=f'resnet ({self.model_name})',
            dtype=self.param_dtype)

    @staticmethod
    def _forward(params, batch, arch, dtype=None):
        from video_features_tpu.ops.precision import features_to_f32
        from video_features_tpu.ops.quant import dequantize_tree
        # int8 lane: expand QuantizedTensor weights in-graph (one
        # convert+multiply each); structural identity — zero ops, same
        # StableHLO — on the fp32/bf16 lanes' plain trees
        params = dequantize_tree(params, dtype)
        x = to_float_zero_one(batch, dtype)
        x = normalize(x, resnet_model.MEAN, resnet_model.STD)
        return features_to_f32(
            resnet_model.forward(params, x, arch=arch, features=True))

    def host_transform(self, frame: np.ndarray) -> np.ndarray:
        frame = short_side_resize_pil(
            frame, RESIZE_OVERRIDES.get(self.model_name, RESIZE_SIZE))
        return center_crop_host(frame, CROP_SIZE)

    def host_transform_spec(self):
        return ('edge_resize_crop',
                RESIZE_OVERRIDES.get(self.model_name, RESIZE_SIZE),
                CROP_SIZE, 'bilinear')

    def device_step(self, batch: np.ndarray) -> jax.Array:
        # aot_call: resident/store-loaded executable when the aot store
        # is on (byte-identical), else exactly the jit call
        return self.aot_call('step', self._step, self.params, batch)

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        from video_features_tpu.ops.nn import linear
        from video_features_tpu.ops.quant import dequantize_tree
        from video_features_tpu.utils.preds import show_predictions_on_dataset
        import jax.numpy as jnp
        logits = np.asarray(linear(jnp.asarray(feats),
                                   dequantize_tree(self.params['fc'])))
        show_predictions_on_dataset(logits, 'imagenet1k')
