"""Streaming stack-window assembly shared by the stack-based extractors.

The reference loads entire videos into RAM before slicing stacks
(reference extract_r21d.py:72-74 — "could run out of memory"; the i3d loop
holds every decoded frame too). Here frames stream off the decoder through
a bounded ring buffer and windows are emitted as soon as they complete, so
memory is O(window) and — wrapped in ``io.video.prefetch`` — decode overlaps
device compute.

Windowing semantics are exactly ``utils.slicing.form_slices``: window k
starts at ``k·step``; only full windows are emitted (partial final stacks
are dropped, like the reference, extract_i3d.py:126-129).

``stream_windows_across_videos`` extends the windower across video
boundaries for the packed corpus mode (``parallel.packing``): one
fault-isolated stream over the whole worklist, so device batches can fill
with windows from several videos instead of padding at every video's tail.
"""
from __future__ import annotations

import math
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from video_features_tpu.utils.tracing import NULL_TRACER, Tracer


def iter_batched_windows(windows: Iterable[np.ndarray],
                         batch: int) -> Iterator[tuple]:
    """Group streamed windows into fixed-size ``(stacks, valid, window_idx)``
    batches: a (batch, ...) array whose tail is padded by repeating the last
    window (mask with ``[:valid]``) plus the absolute index of the batch's
    first window. Generator form so a caller can map a device transfer over
    it inside ``io.video.prefetch`` — batch assembly AND host→device copy
    then run on the producer thread, overlapped with device compute.
    """
    pending: List[np.ndarray] = []
    window_idx = 0

    def flush():
        valid = len(pending)
        while len(pending) < batch:
            pending.append(pending[-1])
        out = (np.stack(pending), valid, window_idx)
        pending.clear()
        return out, valid

    for window in windows:
        pending.append(window)
        if len(pending) == batch:
            out, valid = flush()
            yield out
            window_idx += valid
    if pending:
        yield flush()[0]


def transfer_batches(items: Iterable[tuple], put, keep_host: bool = False,
                     tracer: Tracer = NULL_TRACER,
                     depth: int = 2) -> Iterator[tuple]:
    """Overlap host→device input transfer with device compute.

    ``items`` yields ``(host_batch, *meta)``; ``put`` places one batch on
    the device(s) (``BaseExtractor.put_input``). Returns a prefetched
    iterator of ``(device_batch, host_batch | None, *meta)`` where the
    async copy of batch k+1 starts on the producer thread while the
    consumer runs batch k. ``depth`` (default 2) is how many transferred
    batches the producer thread STAGES ahead of the consumer: at 2 the
    next batch's ``device_put`` is always already issued while the
    current batch runs, so the transfer never lands on the dispatch
    critical path even when the consumer momentarily outruns the
    producer (h2d was a 6–11.5% share serialized before dispatch in
    BENCH_r05). Each staged unit keeps one more input batch resident on
    device; ``depth=1`` restores the minimal single-buffer overlap.
    ``keep_host=True`` carries the host array alongside (debug surfaces
    like show_pred read pixels without paying a D2H round trip). The
    single home for this transfer policy — every batched extractor
    drives its device loop through here. ``tracer`` attributes the
    producer-thread transfer time to the ``h2d`` stage (it runs outside
    the extract loop, so without this it would be invisible in the
    profile table); the span's ``staged`` attr records whether the
    transfer was issued ahead of need (depth > 1) or on demand.

    Backend caveat (measured on the axon remote-TPU tunnel): some remote
    backends DEFER the physical copy of an async ``device_put`` until a
    computation consumes the buffer, and transfer + compute share one
    connection — host-side prefetch then reorders but cannot hide the
    copy, and forcing eager materialization (dispatching a reduction over
    the buffer from the producer thread) only adds a round trip. On real
    TPU hosts ``device_put`` copies eagerly over PCIe and this prefetch
    genuinely overlaps.
    """
    from video_features_tpu.io.video import prefetch

    depth = max(int(depth or 1), 1)
    staged = depth > 1

    def to_device(item):
        batch = item[0]
        if batch is None:
            # batchless scheduler marker (packed NUDGE): nothing to copy
            return (None, None) + tuple(item[1:])
        host = batch if keep_host else None
        with tracer.stage('h2d', staged=staged):
            dev = put(batch)
        return (dev, host) + tuple(item[1:])

    return prefetch(map(to_device, items), depth=depth)


def overlap_fetch(dispatched: Iterable[tuple], fetch, depth: int,
                  tracer: Tracer = NULL_TRACER) -> Iterator[tuple]:
    """Defer device→host readback ``depth`` dispatches behind compute.

    ``dispatched`` yields ``(device_out, *meta)`` where ``device_out``
    is a just-dispatched step's output (device arrays — no forced
    readback yet); items queue until ``depth`` of them are in flight,
    then the OLDEST is materialized with ``fetch`` (timed as the ``d2h``
    stage) and yielded as ``(host_out, *meta)`` — so on async backends
    the readback + whatever the consumer does with the results (feature
    append, save) overlap the device computing the next batches.
    ``depth=1`` is the old synchronous order: every dispatch is
    immediately followed by its fetch. Results always come back in
    dispatch order, so consumers are unchanged beyond the deferral.
    The per-video extract loops drive their device steps through here;
    the packed scheduler (``parallel.packing.run_packed``) implements
    the same policy inline because its sync point also owns scatter and
    fault isolation.
    """
    from collections import deque
    depth = max(int(depth or 1), 1)
    pending: 'deque' = deque()

    def materialize():
        item = pending.popleft()
        with tracer.stage('d2h'):
            host = fetch(item[0])
        return (host,) + tuple(item[1:])

    for item in dispatched:
        pending.append(item)
        if len(pending) >= depth:
            yield materialize()
    while pending:
        yield materialize()


def segment_frame_range(segment, fps) -> Optional[Tuple[int, int]]:
    """Map a ``(start_s, end_s)`` time range onto retimed frame indices.

    The half-open frame range ``[start_f, end_f)`` covers every frame
    whose timestamp falls inside the segment at the loader's OUTPUT
    frame rate (post-retiming — the timebase ``timestamps_ms`` and the
    windower both live in). Conservative rounding (floor start, ceil
    end) so a window that merely touches the boundary is still covered.
    """
    if segment is None:
        return None
    start_s, end_s = float(segment[0]), float(segment[1])
    fps = float(fps)
    return (int(math.floor(start_s * fps)),
            max(int(math.ceil(end_s * fps)), 0))


def framewise_segment_windows(batches: Iterable,
                              frame_range: Optional[Tuple[int, int]],
                              ) -> Iterator[tuple]:
    """Per-frame ``(frame, t_ms)`` windows from a loader's batch stream,
    honoring an optional half-open frame range with early decode stop —
    the ONE home for the frame-wise segment filter, shared by
    ``BaseFrameWiseExtractor.packed_windows`` and the farm's
    ``FramewiseRecipe`` so the in-process and worker-process paths can
    never diverge on the boundary rule (byte-parity is tested, but only
    a shared implementation makes it structural)."""
    for batch, times, indices in batches:
        for frame, t_ms, idx in zip(batch, times, indices):
            if frame_range is not None:
                if idx < frame_range[0]:
                    continue                  # before the range: drop
                if idx >= frame_range[1]:
                    return                    # past it: stop decoding
            yield np.asarray(frame), t_ms


def stream_windows_across_videos(tasks: Iterable,
                                 open_windows: Callable) -> Iterator[tuple]:
    """The corpus-mode windower: yield ``(task, window, meta)`` across video
    boundaries so a downstream packer can fill device batches from the whole
    worklist instead of draining one video at a time.

    ``tasks`` iterates scheduler tasks (``parallel.packing.VideoTask``);
    ``open_windows(task)`` returns that video's ``(window, meta)`` iterator
    (an extractor's ``packed_windows`` hook). Videos are drained in order —
    the tail windows of video k and the head windows of video k+1 land in
    the same stream, which is exactly what lets the packed batch stay full
    at boundaries.

    Per-video fault isolation matches ``BaseExtractor._extract``: an
    exception while opening or decoding one video marks that task failed
    (its partial windows may still flow through a shared batch — harmless,
    they are never saved) and the stream continues with the next video; one
    bad file never kills the worklist nor the batches it shares
    (KeyboardInterrupt re-raises). ``task.emitted``/``task.exhausted`` are
    maintained here — the scatter side uses them to decide when a video's
    features are complete.

    The ``parallel.packing.FLUSH`` sentinel (dynamic sources: the serve
    request feed marks an arrival lull) passes straight through to the
    downstream packer, which flushes its partial geometry pools.
    """
    from video_features_tpu.extract.base import log_extraction_error
    from video_features_tpu.parallel.packing import FLUSH, NUDGE
    for task in tasks:
        if task is FLUSH:
            yield FLUSH
            continue
        try:
            for item in open_windows(task):
                if item is FLUSH:
                    # a LIVE window source (ingress live sessions) marks
                    # an arrival lull mid-video: pass it through so the
                    # packer flushes partial pools and the async loop
                    # materializes — already-computed windows stream back
                    # to the client instead of waiting on future frames
                    yield FLUSH
                    continue
                window, meta = item
                if task.failed:
                    # the consumer failed this video mid-run (device-step
                    # fault): stop decoding the rest of it — only the few
                    # windows already buffered/pooled still flow through
                    # (and are dropped at scatter), instead of the whole
                    # remainder of the video burning decode + device time
                    break
                task.emitted += 1
                yield task, window, meta
        except KeyboardInterrupt:
            raise
        except Exception:
            task.failed = True
            # structured fault report: the serve request id (None for CLI
            # tasks) and the stage that died ride on the log record
            log_extraction_error(
                task.path, stage='decode',
                request_id=getattr(getattr(task, 'request', None), 'id',
                                   None))
        finally:
            task.exhausted = True
        if task.emitted == 0:
            # no batch will ever carry this video's completion (resume
            # skip / too-short clip / failed open): NUDGE the consumer so
            # it finalizes NOW — a dynamic stream may not end for hours
            yield NUDGE


def stream_windows(batches: Iterable, win: int, step: int,
                   tracer: Tracer = NULL_TRACER,
                   stage: str = 'decode',
                   frame_range: Optional[Tuple[int, int]] = None,
                   ) -> Iterator[np.ndarray]:
    """Yield (win, ...)-shaped frame windows from a loader's batch stream.

    ``batches`` iterates ``(batch, times, indices)`` tuples (the VideoLoader
    protocol); decode work inside ``next()`` is timed under ``stage``.

    ``frame_range`` (segment queries) restricts the emitted windows to
    those OVERLAPPING the half-open frame range ``[start_f, end_f)``:
    window k spans frames ``[k·step, k·step + win)``, and the first /
    last covered k follow from that. The iterator stops pulling decode
    batches as soon as the last covered window completes, so decode cost
    is proportional to the covered range's END, never the whole video
    (sequential decoders can't seek, so frames BEFORE the range still
    decode but are dropped without stacking).

    A bare ``parallel.packing.FLUSH`` item in ``batches`` passes through
    untouched (live sessions mark arrival lulls mid-stream) — this is
    what lets the live-session layer run its network frames through THIS
    windower, so live and file-backed windowing can never diverge.
    """
    from video_features_tpu.parallel.packing import FLUSH
    buf: List[np.ndarray] = []
    offset = 0          # absolute frame index of buf[0]
    next_start = 0      # absolute start of the next window
    end_f = None
    if frame_range is not None:
        start_f, end_f = frame_range
        if start_f >= end_f:
            return          # empty range: no window overlaps it
        # first window whose span reaches into the range:
        # k·step + win > start_f
        k_min = max(0, (start_f - win) // step + 1)
        next_start = k_min * step
        if next_start >= end_f:
            return
    for item in tracer.wrap_iter(stage, batches):
        if item is FLUSH:
            yield FLUSH
            continue
        batch = item[0]
        buf.extend(batch)
        # drop frames the next window can no longer touch
        d = min(next_start - offset, len(buf))
        if d > 0:
            del buf[:d]
            offset += d
        while next_start + win <= offset + len(buf):
            s = next_start - offset
            yield np.stack(buf[s:s + win])
            next_start += step
            if end_f is not None and next_start >= end_f:
                return      # past the range: stop decoding the tail
            d = min(next_start - offset, len(buf))
            if d > 0:
                del buf[:d]
                offset += d
