"""Streaming stack-window assembly shared by the stack-based extractors.

The reference loads entire videos into RAM before slicing stacks
(reference extract_r21d.py:72-74 — "could run out of memory"; the i3d loop
holds every decoded frame too). Here frames stream off the decoder through
a bounded ring buffer and windows are emitted as soon as they complete, so
memory is O(window) and — wrapped in ``io.video.prefetch`` — decode overlaps
device compute.

Windowing semantics are exactly ``utils.slicing.form_slices``: window k
starts at ``k·step``; only full windows are emitted (partial final stacks
are dropped, like the reference, extract_i3d.py:126-129).
"""
from __future__ import annotations

from typing import Callable, Iterable, Iterator, List

import numpy as np

from video_features_tpu.utils.tracing import NULL_TRACER, Tracer


def run_batched_windows(windows: Iterable[np.ndarray], batch: int,
                        run: Callable[[np.ndarray, int, int], None]) -> None:
    """Group streamed windows into fixed-size batches and call ``run``.

    ``run(stacks, valid, window_idx)`` receives a (batch, ...) array whose
    tail is padded by repeating the last window (mask with ``[:valid]``)
    and the absolute index of the first window in the batch. Shared by the
    stack-based extractors so the pad/mask/flush bookkeeping exists once.
    """
    pending: List[np.ndarray] = []
    window_idx = 0

    def flush() -> None:
        nonlocal window_idx
        valid = len(pending)
        while len(pending) < batch:
            pending.append(pending[-1])
        stacks = np.stack(pending)
        pending.clear()
        run(stacks, valid, window_idx)
        window_idx += valid

    for window in windows:
        pending.append(window)
        if len(pending) == batch:
            flush()
    if pending:
        flush()


def stream_windows(batches: Iterable, win: int, step: int,
                   tracer: Tracer = NULL_TRACER,
                   stage: str = 'decode') -> Iterator[np.ndarray]:
    """Yield (win, ...)-shaped frame windows from a loader's batch stream.

    ``batches`` iterates ``(batch, times, indices)`` tuples (the VideoLoader
    protocol); decode work inside ``next()`` is timed under ``stage``.
    """
    buf: List[np.ndarray] = []
    offset = 0          # absolute frame index of buf[0]
    next_start = 0      # absolute start of the next window
    for batch, _, _ in tracer.wrap_iter(stage, batches):
        buf.extend(batch)
        # drop frames the next window can no longer touch
        d = min(next_start - offset, len(buf))
        if d > 0:
            del buf[:d]
            offset += d
        while next_start + win <= offset + len(buf):
            s = next_start - offset
            yield np.stack(buf[s:s + win])
            next_start += step
            d = min(next_start - offset, len(buf))
            if d > 0:
                del buf[:d]
                offset += d
