"""timm-style pluggable image-backbone extractor (reference models/timm/).

The reference creates any pip-timm model, resolves its data config, and
strips the classifier (reference models/timm/extract_timm.py:48-60). Here
the backbone registry is native-JAX — the ViT family (models/vit.py) and the
ResNet family (models/resnet.py) cover the curated model space — and a real
``timm`` install (optional) extends it: if timm is importable and
``pretrained=true``, the torch model's state_dict and resolved data config
are transplanted mechanically.

Output parity: {feature_type: (T, D), 'fps', 'timestamps_ms'} and
``show_pred`` top-5 against the ImageNet-1k label map when a classifier head
exists (reference extract_timm.py:63-91 infers the dataset from the hf tag;
our native registry is in1k-headed).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import numpy as np

from video_features_tpu.extract.framewise import BaseFrameWiseExtractor
from video_features_tpu.models import beit as beit_model
from video_features_tpu.models import convnext as convnext_model
from video_features_tpu.models import efficientnet as efficientnet_model
from video_features_tpu.models import mixer as mixer_model
from video_features_tpu.models import mobilenetv3 as mobilenetv3_model
from video_features_tpu.models import regnet as regnet_model
from video_features_tpu.models import resnet as resnet_model
from video_features_tpu.models import swin as swin_model
from video_features_tpu.models import vit as vit_model
from video_features_tpu.ops.transforms import (
    center_crop_host, normalize, resize_pil, to_float_zero_one,
)
from video_features_tpu.utils.device import jax_device


def _data_cfg(family: str, arch: str = '') -> Dict[str, Any]:
    """timm resolve_data_config equivalents for the native families:
    resize = floor(input_size / crop_pct), family-default interpolation."""
    if family == 'efficientnet':
        # per-arch input sizes (timm efficientnet default_cfgs)
        _, _, size, crop_pct = efficientnet_model.ARCHS[arch]
        return dict(resize=int(size / crop_pct), crop=size,
                    interpolation='bicubic',
                    mean=efficientnet_model.MEAN, std=efficientnet_model.STD)
    if family == 'vit':
        # timm vit: crop_pct 0.9, bicubic, 0.5 "inception" stats
        return dict(resize=248, crop=224, interpolation='bicubic',
                    mean=vit_model.MEAN, std=vit_model.STD)
    if family == 'beit':
        # timm beit: same recipe as vit (crop_pct 0.9, bicubic, 0.5 stats)
        return dict(resize=248, crop=224, interpolation='bicubic',
                    mean=beit_model.MEAN, std=beit_model.STD)
    if family == 'mixer':
        # timm mixer _cfg: crop_pct 0.875, bicubic, 0.5 stats
        return dict(resize=256, crop=224, interpolation='bicubic',
                    mean=mixer_model.MEAN, std=mixer_model.STD)
    if family == 'deit':
        # timm deit _cfg: crop_pct 0.9, bicubic, ImageNet stats
        return dict(resize=248, crop=224, interpolation='bicubic',
                    mean=convnext_model.MEAN, std=convnext_model.STD)
    if family == 'convnext':
        # timm convnext default_cfg: crop_pct 0.875, bicubic, ImageNet stats
        return dict(resize=256, crop=224, interpolation='bicubic',
                    mean=convnext_model.MEAN, std=convnext_model.STD)
    if family == 'swin':
        # timm swin default_cfg: crop_pct 0.9, bicubic, ImageNet stats
        return dict(resize=248, crop=224, interpolation='bicubic',
                    mean=swin_model.MEAN, std=swin_model.STD)
    if family == 'regnet':
        # timm regnet _cfg: crop_pct 0.875, bicubic, ImageNet stats
        return dict(resize=256, crop=224, interpolation='bicubic',
                    mean=regnet_model.MEAN, std=regnet_model.STD)
    # resnet and mobilenetv3 share the timm default recipe: crop_pct
    # 0.875, bilinear, ImageNet stats
    return dict(resize=256, crop=224, interpolation='bilinear',
                mean=resnet_model.MEAN, std=resnet_model.STD)


def _registry() -> Dict[str, Dict[str, Any]]:
    reg = {}
    for name, cfg in vit_model.ARCHS.items():
        reg[name] = dict(family='vit', arch=name, feat_dim=cfg['width'])
    # non-distilled DeiT IS timm's VisionTransformer (same module tree and
    # state_dict; only the data config differs) — alias onto the vit archs;
    # distilled variants add dist_token/head_dist (models/vit.py dispatches
    # on the checkpoint's dist_token, so the graph follows the weights)
    for deit, vit_arch in [
        ('deit_tiny_patch16_224', 'vit_tiny_patch16_224'),
        ('deit_small_patch16_224', 'vit_small_patch16_224'),
        ('deit_base_patch16_224', 'vit_base_patch16_224'),
    ]:
        reg[deit] = dict(family='deit', arch=vit_arch,
                         feat_dim=vit_model.ARCHS[vit_arch]['width'])
        dist = deit.replace('_patch', '_distilled_patch')
        reg[dist] = dict(family='deit', arch=vit_arch,
                         feat_dim=vit_model.ARCHS[vit_arch]['width'],
                         init=dict(distilled=True))
    for name, cfg in resnet_model.ARCHS.items():
        reg[name] = dict(family='resnet', arch=name, feat_dim=cfg['feat_dim'])
    for name, cfg in convnext_model.ARCHS.items():
        reg[name] = dict(family='convnext', arch=name,
                         feat_dim=cfg['dims'][-1])
    for name in swin_model.ARCHS:
        reg[name] = dict(family='swin', arch=name,
                         feat_dim=swin_model.feat_dim(name))
    for name in efficientnet_model.ARCHS:
        reg[name] = dict(family='efficientnet', arch=name,
                         feat_dim=efficientnet_model.feat_dim(name))
    for name in regnet_model.ARCHS:
        reg[name] = dict(family='regnet', arch=name,
                         feat_dim=regnet_model.feat_dim(name))
    for name in mobilenetv3_model.ARCHS:
        reg[name] = dict(family='mobilenetv3', arch=name,
                         feat_dim=mobilenetv3_model.feat_dim(name))
    for name in beit_model.ARCHS:
        reg[name] = dict(family='beit', arch=name,
                         feat_dim=beit_model.feat_dim(name))
    for name in mixer_model.ARCHS:
        reg[name] = dict(family='mixer', arch=name,
                         feat_dim=mixer_model.feat_dim(name))
    return reg


REGISTRY = _registry()

# family → native model module (deit shares the vit graph; only the data
# config differs — see _data_cfg)
_MODEL_MODULES = {'vit': vit_model, 'deit': vit_model,
                  'resnet': resnet_model, 'convnext': convnext_model,
                  'swin': swin_model, 'efficientnet': efficientnet_model,
                  'regnet': regnet_model, 'mobilenetv3': mobilenetv3_model,
                  'beit': beit_model, 'mixer': mixer_model}


class ExtractTIMM(BaseFrameWiseExtractor):

    def __init__(self, args) -> None:
        self.model_name = args.model_name
        # hf-hub ids (reference tests/timm/test_timm.py:24) resolve by tail:
        # 'hf_hub:timm/vit_base_patch16_224.augreg_in21k' → vit_base_patch16_224
        name = self.model_name.split(':')[-1].split('/')[-1].split('.')[0]
        if name not in REGISTRY:
            raise NotImplementedError(
                f'model_name {self.model_name!r} is not in the native '
                f'backbone registry: {", ".join(sorted(REGISTRY))}. '
                f'(With pip timm installed, timm checkpoints for these '
                f'architectures transplant via checkpoint_path.)')
        spec = REGISTRY[name]
        self.family, self.arch = spec['family'], spec['arch']
        if self.family in ('beit', 'mixer') and args.get('image_size'):
            # checked before any checkpoint loads: nothing loaded changes it
            raise NotImplementedError(
                f'image_size override is not supported for '
                f'{self.family}: its weights are tied to the checkpoint '
                f'resolution (224) — BEiT via the relative-position-bias '
                f'tables, Mixer via the token-mix MLP width. Use a '
                f'ViT/DeiT model for high-resolution inputs.')
        self._init_kwargs = spec.get('init', {})
        super().__init__(args, feat_dim=spec['feat_dim'])
        if args.get('sequence_parallel') and self.compute_dtype != 'float32':
            # refused BEFORE _load_params: every other compute_dtype
            # refusal fires pre-weights (config time), and this one must
            # not transplant a potentially-GBs checkpoint first
            raise NotImplementedError(
                'sequence_parallel + compute_dtype=bfloat16 is not '
                'supported: the ring-attention kernel\'s online-'
                'softmax accumulators are tuned fp32 end to end '
                '(ops/attention.py) and have no measured bf16 parity '
                'bound — run the fast lane on the standard path, or '
                'sequence-parallel at float32')
        self.data_cfg = _data_cfg(self.family, self.arch)
        self._device = jax_device(self.device)
        # _load_params may refine data_cfg from pip-timm's resolved config,
        # so the image_size override must come AFTER it
        self.params = jax.device_put(self._load_params(args), self._device)
        # image_size overrides the checkpoint's native resolution: the crop
        # becomes image_size and the resize scales to keep the family's
        # crop_pct. For ViT this resamples the pos embed to the larger patch
        # grid (models/vit.py:interpolate_pos_embed); past ~736px the token
        # count crosses BLOCKWISE_THRESHOLD and attention runs blockwise —
        # the high-resolution / long-token production path.
        image_size = args.get('image_size')
        if image_size:
            image_size = int(image_size)
            if self.family in ('vit', 'deit'):
                patch = vit_model.ARCHS[self.arch]['patch']
                if image_size % patch:
                    raise ValueError(
                        f'image_size={image_size} must be a multiple of the '
                        f'patch size ({patch}) for {self.arch}')
            factor = image_size / self.data_cfg['crop']
            self.data_cfg['resize'] = int(round(
                self.data_cfg['resize'] * factor))
            self.data_cfg['crop'] = image_size
        # sequence_parallel=true (ViT/DeiT only): the TOKEN axis of every
        # frame shards over ALL local devices and attention runs as a KV
        # ring over ICI (ops/attention.ring_attention) — the multi-chip
        # long-token path for resolutions whose token count exceeds one
        # chip (pairs with image_size; single-chip long-token inputs use
        # blockwise attention automatically).
        # (sequence_parallel + bfloat16 was already refused above,
        # before the checkpoint loaded)
        self.sequence_parallel = args.get('sequence_parallel', False)
        if self.sequence_parallel:
            if self.family not in ('vit', 'deit'):
                raise NotImplementedError(
                    'sequence_parallel is implemented for the ViT/DeiT '
                    f'families (attention over tokens); {self.family} has '
                    'no token axis to shard')
            if self.data_parallel:
                raise NotImplementedError(
                    'sequence_parallel claims every local device for the '
                    'token axis; combine with data parallelism across '
                    'hosts (multihost=true), not data_parallel=true')
            from video_features_tpu.parallel import (
                make_mesh, put_batch, put_replicated,
            )
            from video_features_tpu.utils.device import jax_devices_all
            devices = jax_devices_all(self.device)
            self._mesh = make_mesh(devices=devices,
                                   time_parallel=len(devices))
            # data axis is 1: put_input replicates each frame batch
            self._put_batch = partial(put_batch, self._mesh)
            mesh, arch = self._mesh, self.arch
            mean, std = self.data_cfg['mean'], self.data_cfg['std']

            def _sp_forward(params, batch):
                x = to_float_zero_one(batch)
                x = normalize(x, mean, std)
                return vit_model.forward_sequence_parallel(
                    params, x, mesh, arch=arch)

            self.params = put_replicated(mesh, self.params)
            self._step = jax.jit(_sp_forward)
            return
        self._step = jax.jit(partial(
            self._forward, family=self.family, arch=self.arch,
            mean=self.data_cfg['mean'], std=self.data_cfg['std'],
            dtype=self.compute_jnp_dtype))

    def _load_params(self, args):
        from video_features_tpu.transplant.torch2jax import (
            load_torch_checkpoint, transplant,
        )
        ckpt = args.get('checkpoint_path')
        if ckpt:
            return load_torch_checkpoint(ckpt, dtype=self.param_dtype)
        if args.get('pretrained', True):  # opt-out for offline runs
            try:  # optional pip timm: pull pretrained weights + data config
                import timm
            except ImportError:
                timm = None
        else:
            timm = None
        if timm is not None:
            # failures past the import (missing checkpoint dep, bad hf id)
            # must propagate — silently falling back to random weights would
            # masquerade as a successful pretrained load
            model = timm.create_model(self.model_name, pretrained=True)
            data = timm.data.resolve_data_config({}, model=model)
            self.data_cfg.update(
                resize=data['input_size'][-1] if data.get('crop_pct') is None
                else int(data['input_size'][-1] / data['crop_pct']),
                crop=data['input_size'][-1],
                interpolation=data.get('interpolation', 'bilinear'),
                mean=tuple(data['mean']), std=tuple(data['std']))
            return transplant(model.state_dict(), dtype=self.param_dtype)
        # no checkpoint and no pip-timm: hard error unless random weights
        # are explicitly allowed (the reference's timm path always loads
        # pretrained weights, extract_timm.py:48)
        from video_features_tpu.extract.weights import require_checkpoint
        require_checkpoint(args, 'checkpoint_path', feature_type='timm',
                           what=f'timm ({self.model_name})')
        init = _MODEL_MODULES[self.family]
        return transplant(init.init_state_dict(arch=self.arch,
                                               **self._init_kwargs),
                          dtype=self.param_dtype)

    @staticmethod
    def _forward(params, batch, family, arch, mean, std, dtype=None):
        from video_features_tpu.ops.precision import features_to_f32
        from video_features_tpu.ops.quant import dequantize_tree
        # int8 lane: expand QuantizedTensor weights in-graph; structural
        # identity (same StableHLO) on the fp32/bf16 lanes' plain trees
        params = dequantize_tree(params, dtype)
        x = to_float_zero_one(batch, dtype)
        x = normalize(x, mean, std)
        return features_to_f32(
            _MODEL_MODULES[family].forward(params, x, arch=arch,
                                           features=True))

    def host_transform(self, frame: np.ndarray) -> np.ndarray:
        frame = resize_pil(frame, self.data_cfg['resize'],
                           interpolation=self.data_cfg['interpolation'])
        return center_crop_host(frame, self.data_cfg['crop'])

    def host_transform_spec(self):
        return ('edge_resize_crop', self.data_cfg['resize'],
                self.data_cfg['crop'], self.data_cfg['interpolation'])

    def device_step(self, batch: np.ndarray) -> jax.Array:
        # aot_call: resident/store-loaded executable when the aot store
        # is on (byte-identical), else exactly the jit call
        return self.aot_call('step', self._step, self.params, batch)

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        if self.family in ('vit', 'deit', 'beit', 'mixer'):
            if 'dist_token' in self.params:
                # timm's distilled inference scores the cls and dist tokens
                # with SEPARATE heads ((head(cls)+head_dist(dist))/2); the
                # pooled features here can't reconstruct the two tokens, so
                # any logits printed from them would misrepresent the model
                # vft-lint: ok=stdout-purity — show_pred narration surface
                print('show_pred: distilled DeiT logits need the separate '
                      'cls/dist tokens (timm deit.py); skipping the top-5 '
                      'table for pooled features')
                return
            head = self.params.get('head')
        elif self.family in ('convnext', 'swin', 'regnet'):
            head = (self.params.get('head') or {}).get('fc')
        elif self.family in ('efficientnet', 'mobilenetv3'):
            head = self.params.get('classifier')
        else:
            head = self.params.get('fc')
        if not head:
            return
        import jax.numpy as jnp
        from video_features_tpu.ops.nn import linear
        from video_features_tpu.ops.quant import dequantize_tree
        from video_features_tpu.utils.preds import show_predictions_on_dataset
        logits = np.asarray(linear(jnp.asarray(feats),
                                   dequantize_tree(head)))
        show_predictions_on_dataset(logits, 'imagenet1k')
