"""VGGish audio extractor (reference models/vggish/extract_vggish.py).

Behavior parity: .mp4 input is demuxed mp4 → aac → wav with ffmpeg (tmp
files removed unless ``keep_tmp_files``); .wav input is used directly;
anything else raises. Output is {'vggish': (Ta, 128)}, Ta = duration/0.96
(reference extract_vggish.py:31-62, docs/models/vggish.md:9).

TPU-first: the log-mel DSP runs on the host (float64 numpy, microseconds),
and ALL 0.96 s examples go through the jitted VGG in fixed-size padded
batches so one executable serves any clip length.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Dict

import jax
import numpy as np

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.models import vggish as vggish_model
from video_features_tpu.ops.audio import waveform_to_examples
from video_features_tpu.utils.device import jax_device

BATCH = 32  # compiled example-batch size (a 30 s clip is ~31 examples)


class ExtractVGGish(BaseExtractor):

    # the PCA postprocess matrices are committed to the build device;
    # serve placement (place_on) must migrate them with the params or a
    # placed entry would feed the jitted postprocess operands committed
    # to two different chips
    _device_buffer_attrs = ('_pca_eig', '_pca_means')

    def __init__(self, args) -> None:
        super().__init__(
            feature_type=args.feature_type,
            on_extraction=args.on_extraction,
            tmp_path=args.tmp_path,
            output_path=args.output_path,
            keep_tmp_files=args.keep_tmp_files,
            device=args.device,
            profile=args.get('profile', False),
            precision=args.get('precision', 'highest'),
            compute_dtype=args.get('compute_dtype', 'float32'),
        )
        if args.show_pred:
            raise NotImplementedError('vggish has no show_pred (reference '
                                      'extract_vggish.py:25-26)')
        self.output_feat_keys = [self.feature_type]
        # 0.96 s examples per device step; global batch under data_parallel
        self.example_batch = args.get('batch_size') or BATCH
        self.data_parallel = args.get('data_parallel', False)
        # mp4 audio backend: 'ffmpeg' = the reference's mp4→aac→wav
        # subprocess chain (exact parity, needs an ffmpeg binary); 'native'
        # = in-process libav demux+decode+resample straight to mono 16 kHz
        # float (no temp files, no binary); 'auto' = ffmpeg when present.
        self.audio_backend = args.get('audio_backend', 'auto')
        assert self.audio_backend in ('auto', 'ffmpeg', 'native'), \
            self.audio_backend
        # AudioSet-compatible PCA-whiten + uint8 quantization: off by default
        # (the reference's forward(post_process=False) bypasses its vendored
        # Postprocessor, vggish_slim.py:150-156) but available for users who
        # need YouTube-8M/AudioSet-format embeddings. Validate before the
        # (expensive) checkpoint load so misconfiguration fails fast.
        self.post_process = args.get('post_process', False)
        pca_path = args.get('pca_params_path')
        if self.post_process and not pca_path:
            raise ValueError(
                'post_process=true needs pca_params_path=<vggish_pca_params.npz>')
        self._device = jax_device(self.device)
        self.params = jax.device_put(self.load_params(args), self._device)
        if self.compute_dtype == 'bfloat16':
            # bf16 fast lane: examples ship bf16 (half the H2D bytes —
            # _run_batched casts at the device edge), the VGG runs bf16,
            # features leave as float32 like every lane's contract
            from video_features_tpu.ops.precision import features_to_f32

            def _bf16_forward(params, x):
                return features_to_f32(vggish_model.forward(params, x))

            self._step = jax.jit(_bf16_forward)
        else:
            self._step = jax.jit(vggish_model.forward)
        if self.post_process:
            pca = np.load(pca_path)
            self._pca_eig = jax.device_put(
                pca['pca_eigen_vectors'].astype(np.float32), self._device)
            self._pca_means = jax.device_put(
                pca['pca_means'].astype(np.float32).reshape(-1), self._device)

    def load_params(self, args):
        from video_features_tpu.extract.weights import load_or_init
        return load_or_init(args, 'checkpoint_path',
                            vggish_model.init_state_dict,
                            feature_type='vggish', dtype=self.param_dtype)

    def program_specs(self, mesh=None):
        """vft-programs abstract step spec: one fixed-size batch of
        0.96 s log-mel examples into the jitted VGG. The batch dtype is
        float32 BY CONTRACT — the host DSP runs float64 for reference
        parity and :meth:`extract` pins the narrowing cast at the device
        boundary (the no-f64 rule holds the program side of that line).
        Under the bf16 fast lane the batch ships bf16 (``_run_batched``
        narrows at the device edge — half the H2D bytes), which the lock
        variant's batch dtype records."""
        from video_features_tpu.analysis.programs import ProgramSpec
        if mesh is None:
            b = self.example_batch
        else:
            # vggish has no packed path: its real multi-device program
            # is in-graph data_parallel, whose global batch is
            # example_batch ROUNDED UP to the data axis (_ensure_mesh →
            # round_batch_to_data_axis) — not the packed families'
            # capacity × ndev plan. Pin the program production compiles.
            from video_features_tpu.parallel.mesh import (
                round_batch_to_data_axis,
            )
            b = round_batch_to_data_axis(self.example_batch, mesh)
        batch = self._abstract_batch((b, 96, 64, 1), self.param_dtype, mesh)
        return [ProgramSpec('step', self._step,
                            (self._abstract_params(mesh), batch))]

    def _read_audio(self, video_path: str):
        """(waveform, sr, tmp_files_to_clean) for any supported input."""
        from video_features_tpu.io.audio import extract_wav_from_mp4, read_wav
        from video_features_tpu.io.video import which_ffmpeg

        ext = Path(video_path).suffix
        if ext == '.wav':
            data, sr = read_wav(video_path)
            return data, sr, ()
        if ext != '.mp4':
            raise NotImplementedError(f'unsupported extension {ext}')

        backend = self.audio_backend
        if backend == 'auto':
            if which_ffmpeg():
                backend = 'ffmpeg'
            else:
                from video_features_tpu.io import native
                if not native.available():
                    raise RuntimeError(
                        'no mp4 audio backend available: install an ffmpeg '
                        'binary (audio_backend=ffmpeg) or a C++ toolchain + '
                        'libav dev packages for the in-process decoder '
                        '(audio_backend=native)')
                backend = 'native'
        if backend == 'native':
            from video_features_tpu.io.native import read_audio_native
            from video_features_tpu.ops.audio import SAMPLE_RATE
            data, sr = read_audio_native(video_path, SAMPLE_RATE)
            return data.astype(np.float64), sr, ()
        wav_path, aac_path = extract_wav_from_mp4(video_path, self.tmp_path)
        try:
            data, sr = read_wav(wav_path)
        except Exception:
            # the temp files are bound here, not yet at the caller: clean up
            # so a malformed wav can't leak them
            if not self.keep_tmp_files:
                for p in (wav_path, aac_path):
                    if p and os.path.exists(p):
                        os.remove(p)
            raise
        return data, sr, (wav_path, aac_path)

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        tmp_files = ()
        try:
            with self.tracer.stage('audio_dsp'):
                data, sr, tmp_files = self._read_audio(video_path)
                examples = waveform_to_examples(data, sr)  # (N, 96, 64)
            # The DSP above is float64 BY DESIGN (reference-parity host
            # math); the device program is float32 BY CONTRACT
            # (PROGRAMS.lock.json pins the batch dtype — the no-f64
            # rule). Narrow HERE, explicitly: jax used to apply the same
            # double→float cast silently at device_put (x64 disabled),
            # which is exactly the invisible promotion seam the rule
            # exists to keep pinned. Byte-identical to the implicit
            # path — tests/test_programs.py holds the parity.
            with self.tracer.stage('model'):
                feats = self._run_batched(
                    examples.astype(np.float32)[..., None])  # NHWC
            if self.post_process:
                feats = np.asarray(vggish_model.postprocess(
                    self._pca_eig, self._pca_means, feats)).astype(np.uint8)
        finally:
            if not self.keep_tmp_files:
                for p in tmp_files:
                    if p and os.path.exists(p):
                        os.remove(p)
        return {self.feature_type: feats}

    def _run_batched(self, examples: np.ndarray) -> np.ndarray:
        if self.data_parallel:
            self._ensure_mesh('example_batch')
        n = examples.shape[0]
        if n == 0:
            return np.zeros((0, vggish_model.FEAT_DIM), np.float32)
        if self.compute_dtype == 'bfloat16':
            # the device edge of the bf16 fast lane: examples narrow to
            # bf16 HERE (host-side, before device_put) so the H2D
            # transfer ships half the bytes — the step's graph then runs
            # bf16 end to end with the ops/nn.py fp32 islands
            examples = examples.astype(self.param_dtype)
        B = self.example_batch
        out = []
        with self.precision_scope():
            for start in range(0, n, B):
                chunk = examples[start:start + B]
                valid = chunk.shape[0]
                if valid < B:
                    pad = np.repeat(chunk[-1:], B - valid, axis=0)
                    chunk = np.concatenate([chunk, pad], axis=0)
                if self._mesh is not None:
                    chunk = self._put_batch(chunk)
                # aot_call: resident/store-loaded executable when the
                # aot store is on (byte-identical), else the jit call
                out.append(np.asarray(self.aot_call(
                    'step', self._step, self.params, chunk))[:valid])
        return np.concatenate(out, axis=0)
